"""tools/lint domain passes — JAX001–JAX004 jit-hygiene, LCK001–LCK004
lock discipline + cross-function lock order, DET001/DET002 determinism,
STM001 state-machine exhaustiveness, OBS001–OBS004 observability
closure, CHS001 chaos-catalog closure, WIRE001 wire-key closure, SYN001
host-sync hygiene, ARC001 import layering. Every code must fire on its
module's offender fixture and stay silent on the clean idiom; the
cross-file passes are additionally proven on mutated copies of the real
repo files (delete a handler / add a fake state → the pass fails naming
exactly what is missing). The parse-count spy pins the ProjectIndex
engine to ONE parse per file per full run."""

import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import lint  # noqa: E402  (the tools/lint package; shadows the shim)
from lint import (chaos_check, crash_check, dataflow, determinism,  # noqa: E402
                  exc_contracts, exc_kill, exc_swallow, jax_hygiene, layering,
                  lock_discipline, lock_order, obs_check, stale_taint,
                  state_machine, sync_check, thread_discipline, wire_check)
from lint.index import as_index  # noqa: E402
from lint.registry import REGISTRY  # noqa: E402


def run_lint(tmp_path, source, name="case.py"):
    f = tmp_path / name
    f.write_text(source)
    return lint.lint_file(f)


def codes(findings):
    return [f.split(": ")[1].split(" ")[0] for f in findings]


# --------------------------------------------------------------- registry

def test_registry_has_all_passes():
    names = {c.name for c in REGISTRY}
    assert {"generic", "jax-hygiene", "lock-discipline", "lock-order",
            "determinism", "state-machine", "obs-journey",
            "obs-attribution", "obs-slo", "obs-timeline", "obs-usage",
            "chaos-closure",
            "crash-closure", "wire-closure",
            "sync-hygiene", "thread-discipline", "import-layering",
            "exc-contracts", "exc-swallow", "exc-kill",
            "stale-taint"} <= names
    all_codes = lint.all_codes()
    assert {"JAX001", "JAX002", "JAX003", "JAX004", "LCK001", "LCK002",
            "LCK003", "LCK004", "DET001", "DET002", "STM001", "OBS001",
            "OBS002", "OBS003", "OBS004", "OBS005", "CHS001", "CRS001",
            "WIRE001",
            "SYN001",
            "THR001", "GRD001", "ARC001", "EXC001", "EXC002", "EXC003",
            "STL001"} <= set(all_codes)
    # codes are globally unique across checks
    per_check = [set(c.codes) for c in REGISTRY]
    assert sum(map(len, per_check)) == len(set().union(*per_check))


@pytest.mark.parametrize("mod", [jax_hygiene, lock_discipline, exc_swallow])
def test_every_file_check_ships_fixture_pairs(mod):
    """The plugin contract: one firing offender and one silent clean
    fixture per code, carried by the check module itself."""
    assert set(mod.OFFENDERS) == set(mod.CODES)
    assert set(mod.CLEAN) == set(mod.CODES)


@pytest.mark.parametrize("mod", [jax_hygiene, lock_discipline])
def test_offender_fixtures_fire(mod, tmp_path):
    for code, src in mod.OFFENDERS.items():
        found = run_lint(tmp_path, src, name=f"off_{code}.py")
        assert code in codes(found), (code, found)


@pytest.mark.parametrize("mod", [jax_hygiene, lock_discipline])
def test_clean_fixtures_stay_silent(mod, tmp_path):
    for code, src in mod.CLEAN.items():
        found = run_lint(tmp_path, src, name=f"clean_{code}.py")
        assert found == [], (code, found)


# ------------------------------------------------------------ JAX hygiene

def test_jax_wrapper_returning_idiom_resolved(tmp_path):
    """`return jax.jit(train_step, ...)` over a local def (the
    parallel/fsdp.py / long_context.py idiom) marks the def as traced."""
    src = '''
import jax
import time

def make_train_step(optimizer):
    def train_step(state, tokens):
        t0 = time.time()
        return state, t0
    return jax.jit(train_step, donate_argnums=(0,))
'''
    found = run_lint(tmp_path, src)
    assert codes(found) == ["JAX001"] and "time.time" in found[0]


def test_jax_partial_alias_hop_resolved(tmp_path):
    """`kernel = partial(fn, ...)` then `pl.pallas_call(kernel, ...)`
    (the models/paged.py idiom) traces fn — through either arm of a
    conditional alias."""
    src = '''
import jax.experimental.pallas as pl
from functools import partial
import numpy as np

def _kernel_a(ref):
    return np.random.rand()

def _kernel_b(ref):
    return np.random.rand()

def dispatch(quant):
    if quant:
        kernel = partial(_kernel_a, n=1)
    else:
        kernel = partial(_kernel_b, n=1)
    return pl.pallas_call(kernel, grid=(1,))
'''
    found = run_lint(tmp_path, src)
    assert codes(found) == ["JAX002", "JAX002"]


def test_jax_static_argnames_exempt_from_host_sync(tmp_path):
    """float()/int() on a static_argnames parameter is concrete at trace
    time — silent; the same cast on a traced parameter fires."""
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("temperature",))
def sample(logits, temperature):
    scale = float(temperature)     # static: fine
    return logits * scale

@jax.jit
def bad(logits, temperature):
    return logits * float(temperature)   # traced: host sync
'''
    found = run_lint(tmp_path, src)
    assert codes(found) == ["JAX003"] and "temperature" in found[0]


def test_jax_shard_map_first_arg_traced(tmp_path):
    src = '''
import jax

def build(mesh, specs):
    def shard_gen(params, prompt):
        print("tracing", prompt.shape)
        return params
    return jax.shard_map(shard_gen, mesh=mesh, in_specs=specs,
                         out_specs=specs)
'''
    assert codes(run_lint(tmp_path, src)) == ["JAX001"]


def test_jax_nested_def_inherits_traced(tmp_path):
    src = '''
import jax
import random

@jax.jit
def outer(x):
    def body(carry, _):
        return carry + random.random(), None
    return jax.lax.scan(body, x, None, length=4)[0]
'''
    assert codes(run_lint(tmp_path, src)) == ["JAX002"]


def test_jax_item_call_fires(tmp_path):
    src = '''
import jax

@jax.jit
def step(x):
    return x.sum().item()
'''
    found = run_lint(tmp_path, src)
    assert codes(found) == ["JAX003"] and ".item()" in found[0]


def test_jax_suppression_hatch(tmp_path):
    src = '''
import jax
import time

@jax.jit
def step(x):
    t0 = time.time()  # lint: ignore
    return x + t0
'''
    assert run_lint(tmp_path, src) == []


# --------------------------------------------------------- lock discipline

def test_lck001_acquire_then_adjacent_try_finally_ok(tmp_path):
    src = lock_discipline.CLEAN["LCK001"]
    assert run_lint(tmp_path, src) == []


def test_lck001_message_names_receiver(tmp_path):
    found = run_lint(tmp_path, lock_discipline.OFFENDERS["LCK001"])
    assert "LOCK.acquire()" in found[0]


def test_lck002_nested_with_still_flagged(tmp_path):
    src = '''
import threading
import subprocess

class Refresher:
    def __init__(self):
        self._cache_lock = threading.Lock()

    def refresh(self):
        with self._cache_lock:
            if True:
                subprocess.check_output(["kubectl", "get", "nodes"])
'''
    found = run_lint(tmp_path, src)
    assert codes(found) == ["LCK002"] and "subprocess.check_output" in found[0]


def test_lck002_nested_function_deferred_not_flagged(tmp_path):
    src = '''
import threading
import time

class Scheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = []

    def schedule(self):
        with self._lock:
            def job():
                time.sleep(5)      # runs later, lock not held
            self.jobs.append(job)
'''
    assert run_lint(tmp_path, src) == []


def test_lck003_reports_unguarded_write_line(tmp_path):
    found = run_lint(tmp_path, lock_discipline.OFFENDERS["LCK003"])
    assert codes(found) == ["LCK003"]
    assert "self.draining" in found[0] and "_lock" in found[0]


def test_lck003_init_writes_exempt(tmp_path):
    src = lock_discipline.CLEAN["LCK003"]
    assert run_lint(tmp_path, src) == []


# ------------------------------------------- STM001 (cross-file, mutated)

STM_FILES = [state_machine.CONSTS_PATH, state_machine.STATE_PATH,
             state_machine.METRICS_PATH, state_machine.DIAGRAM_PATH]


def _stm_root(tmp_path, mutate=None):
    """Copy the real state-machine files into a scratch root, optionally
    mutating {relpath: fn(source) -> source}."""
    root = tmp_path / "repo"
    for rel in STM_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_stm001_real_repo_files_pass(tmp_path):
    assert state_machine.run_project(_stm_root(tmp_path)) == []


def test_stm001_deleted_handler_fails_naming_it(tmp_path):
    """Disabling process_drain_nodes must fail twice: the state loses its
    handler, and apply_state still calls the now-missing method."""
    root = _stm_root(tmp_path, mutate={
        state_machine.STATE_PATH: lambda s: s.replace(
            "def process_drain_nodes", "def _disabled_drain_nodes")})
    findings = state_machine.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings, "deleting a handler must fail the pass"
    assert "DRAIN_REQUIRED" in msgs and "no process_* handler" in msgs
    assert "process_drain_nodes" in msgs  # the dangling call site


def test_stm001_fake_state_fails_every_facet(tmp_path):
    root = _stm_root(tmp_path, mutate={
        state_machine.CONSTS_PATH: lambda s: s.replace(
            '    FAILED = "upgrade-failed"',
            '    FAILED = "upgrade-failed"\n    LIMBO = "limbo-required"')})
    findings = state_machine.run_project(root)
    msgs = [m for (_, _, _, m) in findings]
    assert any("LIMBO" in m and "no process_* handler" in m for m in msgs)
    assert any("LIMBO" in m and "UpgradeState.ALL" in m for m in msgs)
    assert any("LIMBO" in m and "metrics" in m for m in msgs)
    assert any("LIMBO" in m and "diagram" in m for m in msgs)


def test_stm001_state_dropped_from_all_is_caught(tmp_path):
    """ALL is the manually-maintained closure metrics iterate — a member
    silently removed from it must fail."""
    root = _stm_root(tmp_path, mutate={
        state_machine.CONSTS_PATH: lambda s: s.replace(
            "VALIDATION_REQUIRED, UNCORDON_REQUIRED, DONE, FAILED)",
            "VALIDATION_REQUIRED, UNCORDON_REQUIRED, DONE)")})
    findings = state_machine.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "FAILED" in msgs and "UpgradeState.ALL" in msgs


# ----------------------------------- STM001 health facet (cross-file, mutated)

HEALTH_FILES = STM_FILES + [state_machine.HEALTH_CONSTS_PATH,
                            state_machine.HEALTH_REMEDIATION_PATH,
                            state_machine.HEALTH_METRICS_PATH,
                            state_machine.HEALTH_DOC_PATH]


def _health_root(tmp_path, mutate=None):
    root = tmp_path / "repo"
    for rel in HEALTH_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_stm001_health_real_repo_files_pass(tmp_path):
    assert state_machine.run_project(_health_root(tmp_path)) == []


def test_stm001_health_facet_skipped_without_health_package(tmp_path):
    """Legacy fixture roots carrying only the upgrade machine still lint
    (the real repo always has health/consts.py)."""
    assert state_machine.run_project(_stm_root(tmp_path)) == []


def test_stm001_health_deleted_handler_entry_fails(tmp_path):
    """Removing a verdict's entry from the remediator's handlers() mapping
    must fail naming the verdict."""
    root = _health_root(tmp_path, mutate={
        state_machine.HEALTH_REMEDIATION_PATH: lambda s: s.replace(
            "            HealthVerdict.DEGRADED: self.process_degraded,\n",
            "")})
    findings = state_machine.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings, "deleting a handler entry must fail the pass"
    assert "DEGRADED" in msgs and "no handler entry" in msgs


def test_stm001_health_dangling_mapped_handler_fails(tmp_path):
    """A verdict mapped to a process_* method that no longer exists is the
    delete-the-method-not-the-mapping drift."""
    root = _health_root(tmp_path, mutate={
        state_machine.HEALTH_REMEDIATION_PATH: lambda s: s.replace(
            "def process_degraded", "def _disabled_degraded")})
    findings = state_machine.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "process_degraded" in msgs and "no such process_*" in msgs


def test_stm001_health_fake_verdict_fails_every_facet(tmp_path):
    root = _health_root(tmp_path, mutate={
        state_machine.HEALTH_CONSTS_PATH: lambda s: s.replace(
            '    UNHEALTHY_PERSISTENT = "unhealthy-persistent"',
            '    UNHEALTHY_PERSISTENT = "unhealthy-persistent"\n'
            '    LIMBO = "limbo-required"')})
    findings = state_machine.run_project(root)
    msgs = [m for (_, _, _, m) in findings]
    assert any("LIMBO" in m and "no handler entry" in m for m in msgs)
    assert any("LIMBO" in m and "HealthVerdict.ALL" in m for m in msgs)
    assert any("LIMBO" in m and "metrics" in m for m in msgs)
    assert any("LIMBO" in m and "fleet-health.md" in m for m in msgs)


def test_stm001_health_undocumented_verdict_fails(tmp_path):
    """Gutting docs/fleet-health.md must fail the doc facet for the
    verdicts whose wire value disappears."""
    root = _health_root(tmp_path, mutate={
        state_machine.HEALTH_DOC_PATH:
            lambda s: s.replace("unhealthy-persistent", "redacted")})
    findings = state_machine.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "UNHEALTHY_PERSISTENT" in msgs and "not documented" in msgs


# ------------------------------------------- OBS001 (cross-file, mutated)

OBS_FILES = [obs_check.CONSTS_PATH, obs_check.JOURNEY_PATH,
             obs_check.CHOKE_PATH]


def _obs_root(tmp_path, mutate=None, extra=None):
    """Copy the real journey/threshold/choke-point files into a scratch
    root, optionally mutating {relpath: fn(source) -> source} and adding
    {relpath: source} extras."""
    root = tmp_path / "repo"
    for rel in OBS_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    for rel, src in (extra or {}).items():
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_obs001_real_repo_files_pass(tmp_path):
    assert obs_check.run_project(_obs_root(tmp_path)) == []


def test_obs001_real_repo_passes():
    assert obs_check.run_project(REPO) == []


def test_obs001_missing_threshold_fails_naming_state(tmp_path):
    """Dropping one state's stuck-threshold default must fail naming the
    state (and flag the now-stale situation from neither side silently)."""
    root = _obs_root(tmp_path, mutate={
        obs_check.JOURNEY_PATH: lambda s: s.replace(
            '    "pod-restart-required": 900.0,\n', '')})
    findings = obs_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings, "a missing threshold must fail the pass"
    assert "POD_RESTART_REQUIRED" in msgs and "stuck-threshold" in msgs


def test_obs001_new_state_without_threshold_fails(tmp_path):
    root = _obs_root(tmp_path, mutate={
        obs_check.CONSTS_PATH: lambda s: s.replace(
            '    FAILED = "upgrade-failed"',
            '    FAILED = "upgrade-failed"\n    LIMBO = "limbo-required"')})
    findings = obs_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "LIMBO" in msgs and "stuck-threshold" in msgs


def test_obs001_stale_threshold_key_fails(tmp_path):
    """A threshold key no longer matching any wire value (renamed state)
    is dead configuration and must fail from the journey side."""
    root = _obs_root(tmp_path, mutate={
        obs_check.JOURNEY_PATH: lambda s: s.replace(
            '    "uncordon-required": 600.0,',
            '    "uncordon-required": 600.0,\n    "ghost-state": 60.0,')})
    findings = obs_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "ghost-state" in msgs and "no UpgradeState wire value" in msgs


ROGUE_STATE_WRITE = '''
class Sneaky:
    def __init__(self, client, keys):
        self._client = client
        self._keys = keys

    def force_done(self, name):
        self._client.patch_node_metadata(
            name, labels={self._keys.state_label: "upgrade-done"})
'''

ROGUE_JOURNEY_WRITE = '''
class Sneakier:
    def __init__(self, client, keys):
        self._client = client
        self._keys = keys

    def erase_history(self, name):
        self._client.patch_node_metadata(
            name, annotations={self._keys.journey_annotation: "[]"})
'''


def test_obs001_state_write_outside_choke_point_fires(tmp_path):
    root = _obs_root(tmp_path, extra={
        "k8s_operator_libs_tpu/health/rogue.py": ROGUE_STATE_WRITE})
    findings = obs_check.run_project(root)
    assert len(findings) == 1
    rel, _, code, msg = findings[0]
    assert code == "OBS001" and rel.endswith("health/rogue.py")
    assert "state-label key" in msg and "choke point" in msg


def test_obs001_journey_write_outside_choke_point_fires(tmp_path):
    root = _obs_root(tmp_path, extra={
        "cmd/rogue.py": ROGUE_JOURNEY_WRITE})
    findings = obs_check.run_project(root)
    assert len(findings) == 1
    assert "journey annotation" in findings[0][3]


def test_obs001_literal_key_write_fires_and_reads_stay_silent(tmp_path):
    """Spelling the key as a string literal instead of going through the
    KeyFactory is the sneakiest bypass; plain READS of the label never
    fire (cmd/status.py, health/monitor.py are full of them)."""
    root = _obs_root(tmp_path, extra={
        "k8s_operator_libs_tpu/tpu/rogue.py": (
            'def f(client, name):\n'
            '    client.patch_node_metadata(name, labels={\n'
            '        "tpu.dev/libtpu-driver-upgrade-state": "upgrade-done"'
            '})\n'),
        "k8s_operator_libs_tpu/tpu/reader.py": (
            'def g(node, keys):\n'
            '    return node.metadata.labels.get(keys.state_label)\n')})
    findings = obs_check.run_project(root)
    assert len(findings) == 1
    assert findings[0][0].endswith("tpu/rogue.py")


# ---------------------------------------- OBS002 (attribution, mutated)

OBS2_FILES = [obs_check.CONSTS_PATH, obs_check.ATTRIBUTION_PATH]


def _obs2_root(tmp_path, mutate=None):
    root = tmp_path / "repo2"
    for rel in OBS2_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_obs002_real_repo_files_pass(tmp_path):
    assert obs_check.run_attribution(_obs2_root(tmp_path)) == []


def test_obs002_real_repo_passes():
    assert obs_check.run_attribution(REPO) == []


def test_obs002_missing_phase_fails_naming_state(tmp_path):
    """Dropping a state's window-phase entry must fail naming the state
    — its dwell would silently leak out of attributed windows."""
    root = _obs2_root(tmp_path, mutate={
        obs_check.ATTRIBUTION_PATH: lambda s: s.replace(
            '    "pod-restart-required": "after_restart",\n', '')})
    findings = obs_check.run_attribution(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS002" for (_, _, c, _) in findings)
    assert "POD_RESTART_REQUIRED" in msgs and "window-phase" in msgs


def test_obs002_new_state_without_phase_fails(tmp_path):
    root = _obs2_root(tmp_path, mutate={
        obs_check.CONSTS_PATH: lambda s: s.replace(
            '    FAILED = "upgrade-failed"',
            '    FAILED = "upgrade-failed"\n'
            '    LIMBO = "limbo-required"')})
    findings = obs_check.run_attribution(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "LIMBO" in msgs and "window-phase" in msgs


def test_obs002_stale_key_fails(tmp_path):
    root = _obs2_root(tmp_path, mutate={
        obs_check.ATTRIBUTION_PATH: lambda s: s.replace(
            '    "upgrade-done": "outside",',
            '    "upgrade-done": "outside",\n'
            '    "ghost-state": "outside",')})
    findings = obs_check.run_attribution(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "ghost-state" in msgs and "no UpgradeState wire value" in msgs


def test_obs002_unknown_segment_name_fails(tmp_path):
    """A typo'd segment value would attribute dwell to a phase nothing
    reports — the whitelist catches it."""
    root = _obs2_root(tmp_path, mutate={
        obs_check.ATTRIBUTION_PATH: lambda s: s.replace(
            '    "drain-required": "gate_to_restart",',
            '    "drain-required": "gate_to_restrat",')})
    findings = obs_check.run_attribution(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "gate_to_restrat" in msgs and "not one of" in msgs


# ------------------------------------- OBS003 (SLO catalog, mutated)

OBS3_FILES = [obs_check.SLO_PATH, obs_check.ALERTS_PATH,
              obs_check.METRICS_PATH, obs_check.ROUTER_METRICS_PATH,
              obs_check.PROFILE_PATH, obs_check.MARKET_METRICS_PATH,
              obs_check.RESILIENCE_PATH, obs_check.REQTRACE_PATH,
              obs_check.SLO_CAUSES_PATH]


def _obs3_root(tmp_path, mutate=None, skip=()):
    root = tmp_path / "repo3"
    for rel in OBS3_FILES:
        if rel in skip:
            continue
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_obs003_real_repo_files_pass(tmp_path):
    assert obs_check.run_slo(_obs3_root(tmp_path)) == []


def test_obs003_real_repo_passes():
    assert obs_check.run_slo(REPO) == []


def test_obs003_spec_with_unregistered_metric_fails(tmp_path):
    """A typo'd metric family in a default SLO spec would evaluate to
    "no data" forever — the pass fails naming the SLO and the family."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.SLO_PATH: lambda s: s.replace(
            '"metric": "tpu_operator_drain_duration_seconds"',
            '"metric": "tpu_operator_drain_duration_secondz"')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS003" for (_, _, c, _) in findings)
    assert "drain-latency" in msgs
    assert "tpu_operator_drain_duration_secondz" in msgs


def test_obs003_emitted_family_without_help_fails(tmp_path):
    """A new emitted gauge family with no HELP_TEXTS entry would render
    with the underscores-to-spaces fallback."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.ALERTS_PATH: lambda s: s.replace(
            '    "tpu_operator_alert_firing",',
            '    "tpu_operator_alert_firing",\n'
            '    "tpu_operator_alert_pending",')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "tpu_operator_alert_pending" in msgs
    assert "no HELP_TEXTS entry" in msgs


def test_obs003_stale_help_entry_fails(tmp_path):
    """A tpu_operator_slo_* HELP entry nothing emits is a renamed or
    removed gauge seen from the catalog side."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.METRICS_PATH: lambda s: s.replace(
            '    "tpu_operator_alert_firing":',
            '    "tpu_operator_slo_ghost": "phantom budget gauge",\n'
            '    "tpu_operator_alert_firing":')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "tpu_operator_slo_ghost" in msgs
    assert "no emitted family" in msgs


def test_obs003_non_slo_help_entries_stay_exempt(tmp_path):
    """Only the slo/alert/router prefixes are closed over the emitted
    tables — the rest of the catalog (phase histograms, workload
    families) is owned by other layers and must not fire here."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.METRICS_PATH: lambda s: s.replace(
            '    "tpu_operator_alert_firing":',
            '    "tpu_operator_some_new_histogram": "fine",\n'
            '    "tpu_operator_alert_firing":')})
    assert obs_check.run_slo(root) == []


def test_obs003_router_family_without_help_fails(tmp_path):
    """A new router family in serving/metrics.py with no HELP_TEXTS
    entry would render with the underscores-to-spaces fallback."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.ROUTER_METRICS_PATH: lambda s: s.replace(
            '    "tpu_router_replicas",',
            '    "tpu_router_replicas",\n'
            '    "tpu_router_phantom_gauge",')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS003" for (_, _, c, _) in findings)
    assert "tpu_router_phantom_gauge" in msgs
    assert "no HELP_TEXTS entry" in msgs


def test_obs003_stale_router_help_entry_fails(tmp_path):
    """A tpu_router_* HELP entry nothing emits is a renamed or removed
    router metric seen from the catalog side."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.METRICS_PATH: lambda s: s.replace(
            '    "tpu_router_replicas":',
            '    "tpu_router_ghost": "phantom router gauge",\n'
            '    "tpu_router_replicas":')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "tpu_router_ghost" in msgs
    assert "no emitted" in msgs and "ROUTER_GAUGE_FAMILIES" in msgs


def test_obs003_router_table_gutted_fails(tmp_path):
    """Renaming an emitted-family table away is parse drift, not a
    silent pass."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.ROUTER_METRICS_PATH: lambda s: s.replace(
            "ROUTER_HISTOGRAM_FAMILIES = (",
            "ROUTER_HISTOGRAM_TABLES = (")})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "ROUTER_HISTOGRAM_FAMILIES" in msgs


def test_obs003_no_serving_package_skips_router_closure(tmp_path):
    """A checkout without the serving package (the fixture scratch roots
    of older passes, a stripped deployment) must not fire on its
    tpu_router_* HELP entries — the closure needs both sides present."""
    root = _obs3_root(tmp_path, skip={obs_check.ROUTER_METRICS_PATH})
    assert obs_check.run_slo(root) == []


def test_obs003_market_family_without_help_fails(tmp_path):
    """A new market family in market/metrics.py with no HELP_TEXTS
    entry would render with the underscores-to-spaces fallback."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.MARKET_METRICS_PATH: lambda s: s.replace(
            '    "tpu_market_exchange_rate",',
            '    "tpu_market_exchange_rate",\n'
            '    "tpu_market_phantom_gauge",')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS003" for (_, _, c, _) in findings)
    assert "tpu_market_phantom_gauge" in msgs
    assert "no HELP_TEXTS entry" in msgs


def test_obs003_stale_market_help_entry_fails(tmp_path):
    """A tpu_market_* HELP entry nothing emits is a renamed or removed
    market metric seen from the catalog side."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.METRICS_PATH: lambda s: s.replace(
            '    "tpu_market_exchange_rate":',
            '    "tpu_market_ghost": "phantom market gauge",\n'
            '    "tpu_market_exchange_rate":')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "tpu_market_ghost" in msgs
    assert "no emitted" in msgs and "MARKET_GAUGE_FAMILIES" in msgs


def test_obs003_no_market_package_skips_market_closure(tmp_path):
    """Without market/metrics.py the market closure is skipped entirely
    (like the router closure without a serving package)."""
    root = _obs3_root(tmp_path, skip={obs_check.MARKET_METRICS_PATH})
    assert obs_check.run_slo(root) == []


def test_obs003_profile_family_without_help_fails(tmp_path):
    """A new flight-recorder family in obs/profile.py's emitted tables
    with no HELP_TEXTS entry would render with the fallback HELP."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.PROFILE_PATH: lambda s: s.replace(
            '    "tpu_operator_apiserver_requests_total",',
            '    "tpu_operator_apiserver_requests_total",\n'
            '    "tpu_operator_apiserver_dropped_total",')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS003" for (_, _, c, _) in findings)
    assert "tpu_operator_apiserver_dropped_total" in msgs
    assert "no HELP_TEXTS entry" in msgs


def test_obs003_stale_profile_help_entry_fails(tmp_path):
    """A tpu_operator_apiserver_*/tsdb_*/obs_scrape_* HELP entry nothing
    emits is a renamed or removed flight-recorder metric seen from the
    catalog side."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.METRICS_PATH: lambda s: s.replace(
            '    "tpu_operator_tsdb_series":',
            '    "tpu_operator_tsdb_ghost": "phantom tsdb gauge",\n'
            '    "tpu_operator_tsdb_series":')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "tpu_operator_tsdb_ghost" in msgs
    assert "no emitted" in msgs and "PROFILE_*_FAMILIES" in msgs


def test_obs003_no_profile_module_skips_flight_recorder_closure(tmp_path):
    """Without obs/profile.py the flight-recorder closure is skipped
    entirely (like the router closure without a serving package)."""
    root = _obs3_root(tmp_path, skip={obs_check.PROFILE_PATH})
    assert obs_check.run_slo(root) == []


# ------------------------------------- CHS001 (chaos catalog, mutated)

CHS_FILES = [chaos_check.FAULTS_PATH, chaos_check.SCENARIO_PATH,
             chaos_check.INVARIANTS_PATH]


def _chs_root(tmp_path, mutate=None):
    root = tmp_path / "repo_chs"
    for rel in CHS_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_chs001_real_repo_files_pass(tmp_path):
    assert chaos_check.run_project(_chs_root(tmp_path)) == []


def test_chs001_real_repo_passes():
    assert chaos_check.run_project(REPO) == []


def test_chs001_repo_without_chaos_package_is_silent(tmp_path):
    assert chaos_check.run_project(tmp_path) == []


def test_chs001_new_fault_without_parser_and_coverage_fails(tmp_path):
    """Adding a fault type the parsers/coverage don't know must fail
    naming the fault from BOTH directions."""
    root = _chs_root(tmp_path, mutate={
        chaos_check.FAULTS_PATH: lambda s: s.replace(
            '    "spot-reclaim",',
            '    "spot-reclaim",\n    "power-cut",')})
    findings = chaos_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "CHS001" for (_, _, c, _) in findings)
    assert "power-cut" in msgs
    assert "no scenario parser" in msgs
    assert "no FAULT_COVERAGE entry" in msgs


def test_chs001_dropped_parser_fails_naming_fault(tmp_path):
    root = _chs_root(tmp_path, mutate={
        chaos_check.SCENARIO_PATH: lambda s: s.replace(
            '    "watch-lag": _parse_watch_lag,\n', '')})
    findings = chaos_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "watch-lag" in msgs and "no scenario parser" in msgs


def test_chs001_stale_coverage_key_fails(tmp_path):
    root = _chs_root(tmp_path, mutate={
        chaos_check.INVARIANTS_PATH: lambda s: s.replace(
            '    "conflict-storm": ("budget", "journey"),',
            '    "conflict-storm": ("budget", "journey"),\n'
            '    "meteor-strike": ("budget",),')})
    findings = chaos_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "meteor-strike" in msgs and "no FAULT_TYPES member" in msgs


def test_chs001_unknown_invariant_name_fails(tmp_path):
    root = _chs_root(tmp_path, mutate={
        chaos_check.INVARIANTS_PATH: lambda s: s.replace(
            '"conflict-storm": ("budget", "journey"),',
            '"conflict-storm": ("budget", "vibes"),')})
    findings = chaos_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "vibes" in msgs and "unknown invariant" in msgs


def test_chs001_orphan_invariant_fails(tmp_path):
    """An invariant no fault stresses is a checker that rots silently."""
    root = _chs_root(tmp_path, mutate={
        chaos_check.INVARIANTS_PATH: lambda s: s.replace(
            '    "usage-conservation",\n)',
            '    "usage-conservation",\n    "entropy",\n)')})
    findings = chaos_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "entropy" in msgs and "stressed by no fault" in msgs


# ------------------------------------------------- ARC001 (fake packages)

ARC_LAYERS = {"utils": set(), "core": {"utils"}, "models": {"core"}}


def _arc_root(tmp_path, files):
    root = tmp_path / "arc"
    for rel, src in files.items():
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_arc001_clean_tree_silent(tmp_path):
    root = _arc_root(tmp_path, {
        "pkg/__init__.py": "from .models.m import M\n",
        "pkg/utils/__init__.py": "",
        "pkg/utils/u.py": "X = 1\n",
        "pkg/core/__init__.py": "",
        "pkg/core/c.py": "from ..utils.u import X\n",
        "pkg/models/__init__.py": "",
        "pkg/models/m.py": "from ..core.c import X\nM = X\n",
    })
    assert layering.run_project(root, package="pkg", layers=ARC_LAYERS) == []


def test_arc001_layer_violation_fires(tmp_path):
    root = _arc_root(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/core/__init__.py": "",
        "pkg/core/c.py": "from ..models.m import M\n",
        "pkg/models/__init__.py": "",
        "pkg/models/m.py": "M = 1\n",
        "pkg/utils/__init__.py": "",
    })
    findings = layering.run_project(root, package="pkg", layers=ARC_LAYERS)
    assert len(findings) == 1
    rel, lineno, code, msg = findings[0]
    assert code == "ARC001" and rel.endswith("core/c.py")
    assert "core may not import models" in msg


def test_arc001_cycle_fires_even_when_layer_legal(tmp_path):
    root = _arc_root(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/core/__init__.py": "",
        "pkg/core/a.py": "from .b import Y\nX = 1\n",
        "pkg/core/b.py": "from .a import X\nY = 2\n",
    })
    findings = layering.run_project(root, package="pkg", layers=ARC_LAYERS)
    assert len(findings) == 1
    assert "import cycle" in findings[0][3]
    assert "pkg.core.a" in findings[0][3] and "pkg.core.b" in findings[0][3]


def test_arc001_type_checking_imports_exempt(tmp_path):
    root = _arc_root(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/core/__init__.py": "",
        "pkg/core/c.py": ("from typing import TYPE_CHECKING\n"
                          "if TYPE_CHECKING:\n"
                          "    from ..models.m import M\n"),
        "pkg/models/__init__.py": "",
        "pkg/models/m.py": "M = 1\n",
    })
    assert layering.run_project(root, package="pkg", layers=ARC_LAYERS) == []


def test_arc001_real_repo_layers_match_declared_dag():
    assert layering.run_project(REPO) == []


# ------------------------------------------------------------- CLI surface

def test_python_m_tools_lint_domain_clean():
    out = subprocess.run([sys.executable, "-m", "tools.lint", "--domain"],
                         cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_shim_and_package_agree(tmp_path):
    """`python tools/lint.py <file>` (the historical entry) and the
    package produce identical findings."""
    f = tmp_path / "case.py"
    f.write_text(jax_hygiene.OFFENDERS["JAX001"])
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), str(f)],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 1
    assert [line for line in out.stdout.splitlines() if line] == \
        lint.lint_file(f)


def test_generic_mode_skips_domain_codes(tmp_path):
    f = tmp_path / "case.py"
    f.write_text(lock_discipline.OFFENDERS["LCK002"])
    assert lint.lint_file(f, domain=False) == []
    assert codes(lint.lint_file(f, domain=True)) == ["LCK002"]


# --------------------------------------- DET001/DET002 (package-scoped)

def run_lint_pkg(tmp_path, source, name="case.py"):
    """The determinism pass fires only inside the library package — place
    the fixture under a package-shaped path."""
    d = tmp_path / "k8s_operator_libs_tpu" / "core"
    d.mkdir(parents=True, exist_ok=True)
    f = d / name
    f.write_text(source)
    return lint.lint_file(f)


def test_det_fixture_pairs_shipped():
    assert set(determinism.OFFENDERS) == set(determinism.CODES)
    assert set(determinism.CLEAN) == set(determinism.CODES)


@pytest.mark.parametrize("code", sorted(determinism.CODES))
def test_det_offenders_fire(code, tmp_path):
    found = run_lint_pkg(tmp_path, determinism.OFFENDERS[code],
                         name=f"off_{code.lower()}.py")
    assert code in codes(found), found


@pytest.mark.parametrize("code", sorted(determinism.CODES))
def test_det_clean_fixtures_stay_silent(code, tmp_path):
    found = run_lint_pkg(tmp_path, determinism.CLEAN[code],
                         name=f"clean_{code.lower()}.py")
    assert found == [], found


def test_det_out_of_package_paths_out_of_scope(tmp_path):
    """tests/tools/cmd/bench live outside the replayed surface — the same
    source at a non-package path stays silent."""
    f = tmp_path / "case.py"
    f.write_text(determinism.OFFENDERS["DET001"])
    assert lint.lint_file(f) == []


def test_det_clock_module_itself_exempt(tmp_path):
    d = tmp_path / "k8s_operator_libs_tpu" / "utils"
    d.mkdir(parents=True)
    f = d / "clock.py"
    f.write_text("import time\n\n\ndef wall():\n    return time.time()\n")
    assert lint.lint_file(f) == []


def test_det_alias_and_hatch(tmp_path):
    src = (
        "import time as _t\n"
        "\n"
        "\n"
        "def a():\n"
        "    return _t.monotonic()\n"
        "\n"
        "\n"
        "def b():\n"
        "    return _t.time()  # det: allow — compared against file mtimes\n"
    )
    found = run_lint_pkg(tmp_path, src)
    assert codes(found) == ["DET001"] and "_t.monotonic" in found[0]


def test_det_real_repo_offenders_fixed():
    """The PR's satellite: serde/cachedclient/uploader route through an
    injected Clock, liveclient carries the documented hatch — the pass
    runs clean over the whole package."""
    pkg = REPO / "k8s_operator_libs_tpu"
    det = [line for f in sorted(pkg.rglob("*.py"))
           if "__pycache__" not in f.parts
           for line in lint.lint_file(f)
           if " DET00" in line]
    assert det == [], det


# ------------------------------ THR001/GRD001 (package + cmd scoped)

def test_thr_fixture_pairs_shipped():
    assert set(thread_discipline.OFFENDERS) == set(thread_discipline.CODES)
    assert set(thread_discipline.CLEAN) == set(thread_discipline.CODES)


@pytest.mark.parametrize("code", sorted(thread_discipline.CODES))
def test_thr_offenders_fire(code, tmp_path):
    found = run_lint_pkg(tmp_path, thread_discipline.OFFENDERS[code],
                         name=f"off_{code.lower()}.py")
    assert code in codes(found), found


@pytest.mark.parametrize("code", sorted(thread_discipline.CODES))
def test_thr_clean_fixtures_stay_silent(code, tmp_path):
    found = run_lint_pkg(tmp_path, thread_discipline.CLEAN[code],
                         name=f"clean_{code.lower()}.py")
    assert found == [], found


def test_thr_fires_under_cmd_tree_too(tmp_path):
    """cmd/ binaries spawn the ticker and watch threads — the shim
    closure covers them, not just the package."""
    d = tmp_path / "cmd"
    d.mkdir(parents=True)
    f = d / "somecli.py"
    f.write_text(thread_discipline.OFFENDERS["THR001"])
    assert "THR001" in codes(lint.lint_file(f))


def test_thr_out_of_scope_paths_silent(tmp_path):
    f = tmp_path / "case.py"
    f.write_text(thread_discipline.OFFENDERS["THR001"])
    assert lint.lint_file(f) == []


def test_thr_shim_module_itself_exempt(tmp_path):
    d = tmp_path / "k8s_operator_libs_tpu" / "utils"
    d.mkdir(parents=True)
    f = d / "threads.py"
    f.write_text("import threading\n\n\ndef make():\n"
                 "    return threading.Lock()\n")
    assert lint.lint_file(f) == []


def test_thr_alias_and_hatch(tmp_path):
    src = (
        "import threading as _t\n"
        "\n"
        "\n"
        "def a():\n"
        "    return _t.RLock()\n"
        "\n"
        "\n"
        "def b():\n"
        "    return _t.Lock()  # thr: allow — interpreter-startup guard "
        "before the shim imports\n"
    )
    found = run_lint_pkg(tmp_path, src)
    assert codes(found) == ["THR001"] and "RLock" in found[0]


def test_grd_finding_names_lock_and_both_sites(tmp_path):
    found = run_lint_pkg(tmp_path, thread_discipline.OFFENDERS["GRD001"])
    grd = [f for f in found if " GRD001 " in f]
    assert len(grd) == 1
    msg = grd[0]
    assert "self._lock" in msg            # the lock
    assert "Runtime.drain()" in msg       # the guarded-write site
    assert "Runtime.admitting()" in msg   # the lock-free site
    assert "(line " in msg                # guarded-write line number


def test_grd_lock_free_write_in_other_method_fires(tmp_path):
    src = '''
from ..utils import threads


class Runtime:
    def __init__(self):
        self._lock = threads.make_lock("runtime")
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def reset(self):
        self.count = 0
'''
    found = run_lint_pkg(tmp_path, src)
    assert "GRD001" in codes(found)
    assert any("written lock-free" in f for f in found)


def test_grd_same_method_lock_free_access_silent(tmp_path):
    """Cross-METHOD discipline only: a snapshot read in the same method
    after dropping the lock is the check-then-act idiom the author can
    see locally."""
    src = '''
from ..utils import threads


class Runtime:
    def __init__(self):
        self._lock = threads.make_lock("runtime")
        self.state = {}

    def tick(self):
        with self._lock:
            self.state = {"n": 1}
        return self.state
'''
    found = run_lint_pkg(tmp_path, src)
    assert found == [], found


def test_grd_hatch_respected(tmp_path):
    src = '''
from ..utils import threads


class Runtime:
    def __init__(self):
        self._lock = threads.make_lock("runtime")
        self.draining = False

    def drain(self):
        with self._lock:
            self.draining = True

    def admitting(self):
        return not self.draining  # thr: allow — GIL-atomic bool, stale ok
'''
    assert run_lint_pkg(tmp_path, src) == []


def test_thr_grd_real_repo_clean():
    """The routing satellite: every library/cmd thread, lock and event
    goes through the shim, and every guarded field holds its lock (or
    carries a documented hatch) — zero findings, empty baseline."""
    hits = []
    for tree in ("k8s_operator_libs_tpu", "cmd"):
        for f in sorted((REPO / tree).rglob("*.py")):
            if "__pycache__" in f.parts:
                continue
            hits += [line for line in lint.lint_file(f)
                     if " THR001 " in line or " GRD001 " in line]
    assert hits == [], hits


# ------------------------------------------------ LCK004 (scratch roots)

def _pkg_root(tmp_path, files):
    root = tmp_path / "lck4"
    for rel, src in files.items():
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


LCK4_ABBA = {
    "k8s_operator_libs_tpu/alpha.py": '''
import threading

A_LOCK = threading.Lock()
B_LOCK = threading.Lock()


def forward(registry):
    with A_LOCK:
        _grab_b(registry)


def _grab_b(registry):
    with B_LOCK:
        registry["b"] = True


def backward(registry):
    with B_LOCK:
        with A_LOCK:
            registry["a"] = True
''',
}

LCK4_CONSISTENT = {
    "k8s_operator_libs_tpu/alpha.py": '''
import threading

A_LOCK = threading.Lock()
B_LOCK = threading.Lock()


def forward(registry):
    with A_LOCK:
        _grab_b(registry)


def _grab_b(registry):
    with B_LOCK:
        registry["b"] = True


def backward(registry):
    with A_LOCK:
        with B_LOCK:
            registry["a"] = True
''',
}

LCK4_TRANSITIVE_SLEEP = {
    "k8s_operator_libs_tpu/beta.py": '''
import threading
import time

LOCK = threading.Lock()


def tick(state):
    with LOCK:
        _settle(state)


def _settle(state):
    time.sleep(1.0)
    state["settled"] = True
''',
}

LCK4_SLEEP_OUTSIDE = {
    "k8s_operator_libs_tpu/beta.py": '''
import threading
import time

LOCK = threading.Lock()


def tick(state):
    with LOCK:
        snapshot = dict(state)
    _settle(snapshot)


def _settle(state):
    time.sleep(1.0)
    state["settled"] = True
''',
}

LCK4_CROSS_MODULE_RPC = {
    "k8s_operator_libs_tpu/gamma.py": '''
import threading

from .delta import refresh

LOCK = threading.Lock()


def snapshot(client, cache):
    with LOCK:
        refresh(client, cache)
''',
    "k8s_operator_libs_tpu/delta.py": '''
def refresh(client, cache):
    cache["nodes"] = client.list_nodes()
''',
}


def test_lck004_abba_cycle_fires(tmp_path):
    findings = lock_order.run_project(_pkg_root(tmp_path, LCK4_ABBA))
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "LCK004" for (_, _, c, _) in findings)
    assert "lock-order cycle" in msgs
    assert "alpha.A_LOCK" in msgs and "alpha.B_LOCK" in msgs


def test_lck004_consistent_order_silent(tmp_path):
    assert lock_order.run_project(_pkg_root(tmp_path, LCK4_CONSISTENT)) == []


def test_lck004_transitive_sleep_fires(tmp_path):
    findings = lock_order.run_project(
        _pkg_root(tmp_path, LCK4_TRANSITIVE_SLEEP))
    assert len(findings) == 1
    rel, _, code, msg = findings[0]
    assert code == "LCK004" and rel.endswith("beta.py")
    assert "time.sleep" in msg and "tick -> _settle" in msg


def test_lck004_sleep_outside_lock_silent(tmp_path):
    assert lock_order.run_project(
        _pkg_root(tmp_path, LCK4_SLEEP_OUTSIDE)) == []


def test_lck004_cross_module_client_rpc_fires(tmp_path):
    """The call graph crosses modules: gamma holds its lock across
    delta.refresh, which does a client RPC."""
    findings = lock_order.run_project(
        _pkg_root(tmp_path, LCK4_CROSS_MODULE_RPC))
    assert len(findings) == 1
    assert "client.list_nodes" in findings[0][3]


def test_lck004_real_repo_passes():
    assert lock_order.run_project(REPO) == []


# ------------------------------------------------ WIRE001 (scratch roots)

WIRE_BASE = {
    "k8s_operator_libs_tpu/wire.py": (
        'DOMAIN = "tpu.dev"\n'
        'FOO_LABEL = "tpu.dev/foo"\n'
        'BAR_KEY = "tpu.dev/bar"\n'),
    "k8s_operator_libs_tpu/user.py": (
        'from .wire import BAR_KEY, FOO_LABEL\n'
        '\n'
        'PAIR = (FOO_LABEL, BAR_KEY)\n'),
}


def _wire_root(tmp_path, extra=None, registry=None):
    files = dict(WIRE_BASE)
    if registry is not None:
        files["k8s_operator_libs_tpu/wire.py"] = registry
    files.update(extra or {})
    root = tmp_path / "wire"
    for rel, src in files.items():
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_wire001_closed_root_silent(tmp_path):
    assert wire_check.run_project(_wire_root(tmp_path)) == []


def test_wire001_real_repo_passes():
    assert wire_check.run_project(REPO) == []


def test_wire001_registered_key_as_literal_fires(tmp_path):
    findings = wire_check.run_project(_wire_root(tmp_path, extra={
        "k8s_operator_libs_tpu/rogue.py": 'K = "tpu.dev/foo"\n'}))
    assert len(findings) == 1
    rel, _, code, msg = findings[0]
    assert code == "WIRE001" and rel.endswith("rogue.py")
    assert "spelled as a literal" in msg


def test_wire001_stray_unregistered_literal_fires(tmp_path):
    findings = wire_check.run_project(_wire_root(tmp_path, extra={
        "k8s_operator_libs_tpu/rogue.py": 'K = "tpu.dev/zap"\n'}))
    assert len(findings) == 1
    assert "stray wire-key literal" in findings[0][3]


def test_wire001_domain_fstring_construction_fires(tmp_path):
    findings = wire_check.run_project(_wire_root(tmp_path, extra={
        "k8s_operator_libs_tpu/rogue.py": (
            'from .wire import DOMAIN\n'
            '\n'
            'K = f"{DOMAIN}/zap"\n')}))
    assert len(findings) == 1
    assert "constructed from DOMAIN" in findings[0][3]


def test_wire001_docstring_mentions_stay_silent(tmp_path):
    assert wire_check.run_project(_wire_root(tmp_path, extra={
        "k8s_operator_libs_tpu/prose.py": (
            '"""Writes the tpu.dev/foo label (see wire.py)."""\n'
            '\n'
            '\n'
            'def f():\n'
            '    """Reads tpu.dev/bar back."""\n'
            '    return None\n')})) == []


def test_wire001_dead_registry_key_fires(tmp_path):
    findings = wire_check.run_project(_wire_root(
        tmp_path,
        registry=('DOMAIN = "tpu.dev"\n'
                  'FOO_LABEL = "tpu.dev/foo"\n'
                  'BAR_KEY = "tpu.dev/bar"\n'
                  'GHOST = "tpu.dev/ghost"\n')))
    assert len(findings) == 1
    rel, _, _, msg = findings[0]
    assert rel.endswith("wire.py")
    assert "GHOST" in msg and "referenced nowhere" in msg


def test_wire001_missing_registry_fires(tmp_path):
    root = tmp_path / "empty"
    (root / "k8s_operator_libs_tpu").mkdir(parents=True)
    findings = wire_check.run_project(root)
    assert len(findings) == 1 and "registry module is missing" \
        in findings[0][3]


# ------------------------------------------------- SYN001 (mutated copies)

SYN_FILES = list(sync_check.HOT_FUNCTIONS)


def _syn_root(tmp_path, mutate=None):
    root = tmp_path / "syn"
    for rel in SYN_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


SERVE = "k8s_operator_libs_tpu/models/serve.py"
HARNESS = "k8s_operator_libs_tpu/train/harness.py"


def test_syn001_real_repo_files_pass(tmp_path):
    assert sync_check.run_project(_syn_root(tmp_path)) == []


def test_syn001_real_repo_passes():
    assert sync_check.run_project(REPO) == []


def test_syn001_unhatched_readback_fires(tmp_path):
    """Stripping the `# syn: readback` mark off the batcher's deliberate
    sync exposes it as an unaudited device->host transfer."""
    root = _syn_root(tmp_path, mutate={
        SERVE: lambda s: s.replace(
            "toks = np.asarray(toks)  # syn: readback — the step's ONE "
            "sync; [n, slots]",
            "toks = np.asarray(toks)")})
    findings = sync_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "SYN001" for (_, _, c, _) in findings)
    assert "'toks'" in msgs and "_step_inner" in msgs


def test_syn001_smuggled_sync_in_train_loop_fires(tmp_path):
    """The PR 4 regression: host-syncing the step metrics inside the
    loop instead of at the _block_on boundary."""
    root = _syn_root(tmp_path, mutate={
        HARNESS: lambda s: s.replace(
            "            state, metrics = self._step_fn(state, batch)",
            "            state, metrics = self._step_fn(state, batch)\n"
            '            probe = float(metrics["loss"])')})
    findings = sync_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "float()" in msgs and "'metrics'" in msgs and "run" in msgs


def test_syn001_item_call_fires(tmp_path):
    root = _syn_root(tmp_path, mutate={
        HARNESS: lambda s: s.replace(
            "            state, metrics = self._step_fn(state, batch)",
            "            state, metrics = self._step_fn(state, batch)\n"
            '            loss = metrics["loss"].item()')})
    findings = sync_check.run_project(root)
    assert any(".item()" in m for (_, _, _, m) in findings)


def test_syn001_block_until_ready_outside_boundary_fires(tmp_path):
    root = _syn_root(tmp_path, mutate={
        HARNESS: lambda s: s.replace(
            "            state, metrics = self._step_fn(state, batch)",
            "            state, metrics = self._step_fn(state, batch)\n"
            '            metrics["loss"].block_until_ready()')})
    findings = sync_check.run_project(root)
    assert any("block_until_ready" in m for (_, _, _, m) in findings)


def test_syn001_renamed_hot_path_fails_config_drift(tmp_path):
    """Renaming a guarded hot function without updating HOT_FUNCTIONS is
    config drift — the pass says so instead of silently guarding
    nothing."""
    root = _syn_root(tmp_path, mutate={
        SERVE: lambda s: s.replace("def _step_inner", "def _tick_inner")})
    findings = sync_check.run_project(root)
    assert any("not found" in m and "_step_inner" in m
               for (_, _, _, m) in findings)


# ------------------------------------- engine: parse counts, baseline

def test_full_domain_run_parses_each_file_exactly_once():
    """The ProjectIndex contract: a full --domain run — every file pass
    plus all seven cross-module passes — parses each file ONCE. This is
    the regression gate against sliding back to O(passes × files)."""
    findings, index = lint.run_suite(mode="domain")
    assert findings == [], findings[:5]
    counts = index.parse_counts
    assert counts, "the run parsed nothing?"
    multi = {rel: n for rel, n in counts.items() if n != 1}
    assert multi == {}, f"files parsed more than once: {multi}"
    # the cross-module passes ran off the same index (their guarded files
    # are in the count), and the run covered the whole tree
    assert "k8s_operator_libs_tpu/upgrade/consts.py" in counts
    assert "k8s_operator_libs_tpu/models/serve.py" in counts
    assert len(counts) > 100


def test_baseline_entry_forms(tmp_path):
    missing = lint.load_baseline(tmp_path / "absent.txt")
    assert missing == set()
    bl = tmp_path / "baseline.txt"
    bl.write_text("# comment\n\npkg/x.py:DET001\npkg/y.py:7:LCK004\n")
    entries = lint.load_baseline(bl)
    assert lint._baselined(("pkg/x.py", 3, "DET001", "m"), entries)
    assert lint._baselined(("pkg/x.py", 99, "DET001", "m"), entries)
    assert lint._baselined(("pkg/y.py", 7, "LCK004", "m"), entries)
    assert not lint._baselined(("pkg/y.py", 8, "LCK004", "m"), entries)
    assert not lint._baselined(("pkg/x.py", 3, "DET002", "m"), entries)


def test_format_json_and_github_emitters(capsys):
    findings = [("a/b.py", 3, "DET001", "bare time.time(), use Clock")]
    lint.emit(findings, "json")
    out = capsys.readouterr().out
    import json
    assert json.loads(out) == [{"path": "a/b.py", "line": 3,
                                "code": "DET001",
                                "message": "bare time.time(), use Clock"}]
    lint.emit(findings, "github")
    out = capsys.readouterr().out
    assert out.startswith("::error file=a/b.py,line=3,title=DET001::")


# ----------------------------------------------- OBS003 (resilience half)


def test_obs003_resilience_family_without_help_fails(tmp_path):
    """A new resilient-boundary family with no HELP_TEXTS entry."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.RESILIENCE_PATH: lambda s: s.replace(
            '    "tpu_operator_apiserver_shed_total",',
            '    "tpu_operator_apiserver_shed_total",\n'
            '    "tpu_operator_apiserver_paused_total",')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "tpu_operator_apiserver_paused_total" in msgs
    assert "no HELP_TEXTS entry" in msgs


def test_obs003_resilience_help_covered_by_either_table(tmp_path):
    """The tpu_operator_apiserver_ prefix is shared by the flight
    recorder and the resilient boundary: dropping the breaker gauge from
    the RESILIENCE tables makes its HELP entry stale (matched by
    NEITHER module's emitted set)."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.RESILIENCE_PATH: lambda s: s.replace(
            '    "tpu_operator_apiserver_breaker_state",\n', '')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "tpu_operator_apiserver_breaker_state" in msgs
    assert "RESILIENCE_*_FAMILIES" in msgs


# ----------------------------------------------- OBS003 (reqtrace half)


def test_obs003_reqtrace_family_without_help_fails(tmp_path):
    """A new request-trace family in obs/reqtrace.py's emitted tables
    with no HELP_TEXTS entry would render with the fallback HELP."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.REQTRACE_PATH: lambda s: s.replace(
            '    "tpu_router_traces_dropped",',
            '    "tpu_router_traces_dropped",\n'
            '    "tpu_router_traces_phantom",')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS003" for (_, _, c, _) in findings)
    assert "tpu_router_traces_phantom" in msgs
    assert "emitted request-trace family" in msgs
    assert "no HELP_TEXTS entry" in msgs


def test_obs003_reqtrace_help_covered_by_either_table(tmp_path):
    """The tpu_router_ prefix is shared by the router tier and the
    request flight recorder: renaming a family inside the REQTRACE
    tables makes the old HELP entry stale (matched by NEITHER module's
    emitted set) AND leaves the new name without a HELP entry — both
    directions fire from one mutation."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.REQTRACE_PATH: lambda s: s.replace(
            '    "tpu_router_request_stage_seconds",',
            '    "tpu_router_request_stage_secondz",')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "tpu_router_request_stage_secondz" in msgs
    assert "no HELP_TEXTS entry" in msgs
    assert "tpu_router_request_stage_seconds'" in msgs
    assert "REQTRACE_*_FAMILIES" in msgs


# ----------------------------------------------- OBS003 (causes half)


def test_obs003_causes_counter_joins_alert_closure(tmp_path):
    """The cause engine's counter shares the tpu_operator_alert_ prefix
    with the alert manager: renaming it inside CAUSES_COUNTER_FAMILIES
    makes the old HELP entry stale AND leaves the new name without a
    HELP entry — both directions fire from one mutation."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.SLO_CAUSES_PATH: lambda s: s.replace(
            '    "tpu_operator_alert_attributed_total",',
            '    "tpu_operator_alert_attributed_totalz",')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS003" for (_, _, c, _) in findings)
    assert "tpu_operator_alert_attributed_totalz" in msgs
    assert "no HELP_TEXTS entry" in msgs
    assert "tpu_operator_alert_attributed_total'" in msgs
    assert "CAUSES_COUNTER_FAMILIES" in msgs


# ------------------------------------- OBS004 (fleet timeline, mutated)

OBS4_FILES = [obs_check.TIMELINE_PATH, obs_check.CAUSES_PATH,
              obs_check.ALERTS_PATH, obs_check.REQTRACE_PATH,
              obs_check.RESILIENCE_PATH,
              "k8s_operator_libs_tpu/tpu/operator.py",
              "k8s_operator_libs_tpu/upgrade/node_state_provider.py",
              "k8s_operator_libs_tpu/market/arbiter.py",
              "k8s_operator_libs_tpu/chaos/injector.py"]


def _obs4_root(tmp_path, mutate=None, skip=()):
    root = tmp_path / "repo4"
    for rel in OBS4_FILES:
        if rel in skip:
            continue
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_obs004_real_repo_files_pass(tmp_path):
    assert obs_check.run_timeline(_obs4_root(tmp_path)) == []


def test_obs004_real_repo_passes():
    assert obs_check.run_timeline(REPO) == []


def test_obs004_uncataloged_emitter_kind_fails(tmp_path):
    """A typo'd record_event() kind literal would raise ValueError on
    the first emit — the pass fails naming the kind and the file, and
    the orphaned catalog entry fires from the other direction."""
    root = _obs4_root(tmp_path, mutate={
        obs_check.REQTRACE_PATH: lambda s: s.replace(
            'kind="router-shed", entity=entity,',
            'kind="router-zhed", entity=entity,')})
    findings = obs_check.run_timeline(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS004" for (_, _, c, _) in findings)
    assert "router-zhed" in msgs and "not in the EVENT_KINDS" in msgs
    assert "'router-shed'" in msgs and "no record_event() emitter" in msgs


def test_obs004_catalog_kind_without_emitter_fails(tmp_path):
    """A cataloged kind nothing emits is dead vocabulary the cause
    priors and docs still pretend exists."""
    root = _obs4_root(tmp_path, mutate={
        obs_check.TIMELINE_PATH: lambda s: s.replace(
            '    "chaos-fault",',
            '    "ghost-kind",\n    "chaos-fault",')})
    findings = obs_check.run_timeline(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS004" for (_, _, c, _) in findings)
    assert "'ghost-kind'" in msgs and "no record_event() emitter" in msgs


def test_obs004_hatched_catalog_kind_stays_silent(tmp_path):
    """`# obs: allow — <why>` on the catalog line is the escape hatch
    for kinds a checkout legitimately catalogs without an in-tree
    emitter."""
    root = _obs4_root(tmp_path, mutate={
        obs_check.TIMELINE_PATH: lambda s: s.replace(
            '    "chaos-fault",',
            '    "ghost-kind",  # obs: allow — reserved for plugins\n'
            '    "chaos-fault",')})
    assert obs_check.run_timeline(root) == []


def test_obs004_non_literal_kind_fails(tmp_path):
    """A computed kind= defeats the catalog closure even when it happens
    to be valid at runtime — only literals keep the pass exhaustive."""
    root = _obs4_root(tmp_path, mutate={
        "k8s_operator_libs_tpu/chaos/injector.py": lambda s: s.replace(
            'kind="chaos-fault", entity=entity,',
            'kind=str("chaos-" + "fault"), entity=entity,')})
    findings = obs_check.run_timeline(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS004" for (_, _, c, _) in findings)
    assert "string literal" in msgs
    # ...and the kind simultaneously loses its only emitter
    assert "'chaos-fault'" in msgs and "no record_event() emitter" in msgs


def test_obs004_cause_prior_outside_catalog_fails(tmp_path):
    """A CAUSE_PRIORS key naming no cataloged kind is a prior for an
    event that can never be recorded."""
    root = _obs4_root(tmp_path, mutate={
        obs_check.CAUSES_PATH: lambda s: s.replace(
            '    "breaker-open": 0.9,',
            '    "breaker-opened": 0.9,')})
    findings = obs_check.run_timeline(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS004" for (_, _, c, _) in findings)
    assert "'breaker-opened'" in msgs and "CAUSE_PRIORS" in msgs


def test_obs004_no_timeline_module_skips(tmp_path):
    """A checkout without obs/timeline.py (older fixture scratch roots)
    must not fire at all — the closure needs the catalog side present."""
    root = _obs4_root(tmp_path, skip={obs_check.TIMELINE_PATH})
    assert obs_check.run_timeline(root) == []


def test_obs003_reqtrace_table_gutted_fails(tmp_path):
    """Renaming a reqtrace emitted-family table away is parse drift,
    not a silent pass (mirrors the router-table rule)."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.REQTRACE_PATH: lambda s: s.replace(
            "REQTRACE_GAUGE_FAMILIES = (",
            "REQTRACE_GAUGE_TABLES = (")})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "REQTRACE_GAUGE_FAMILIES" in msgs


# ------------------------------------------------ OBS005 (scratch roots)

OBS5_FILES = [obs_check.USAGE_PATH, obs_check.METRICS_PATH]


def _obs5_root(tmp_path, mutate=None, skip=()):
    root = tmp_path / "repo5"
    for rel in OBS5_FILES:
        if rel in skip:
            continue
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_obs005_real_repo_files_pass(tmp_path):
    assert obs_check.run_usage(_obs5_root(tmp_path)) == []


def test_obs005_real_repo_passes():
    assert obs_check.run_usage(REPO) == []


def test_obs005_catalog_kind_without_rank_fails(tmp_path):
    """A cataloged kind with no KIND_PRIORITY rank makes the first
    _bid() claim raise at runtime — and, having no claim site, it also
    fires as dead vocabulary."""
    root = _obs5_root(tmp_path, mutate={
        obs_check.USAGE_PATH: lambda s: s.replace(
            '    "idle",',
            '    "ghost-kind",\n    "idle",')})
    findings = obs_check.run_usage(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS005" for (_, _, c, _) in findings)
    assert "'ghost-kind'" in msgs and "no KIND_PRIORITY rank" in msgs
    assert "never claimed by any _bid() site" in msgs


def test_obs005_renamed_priority_key_fails_both_ways(tmp_path):
    """Renaming a KIND_PRIORITY key away from its catalog entry fires
    from both directions: a rank nothing can claim AND a kind whose
    claim would raise."""
    root = _obs5_root(tmp_path, mutate={
        obs_check.USAGE_PATH: lambda s: s.replace(
            '    "degraded-frozen": 6,',
            '    "degraded-f": 6,')})
    findings = obs_check.run_usage(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS005" for (_, _, c, _) in findings)
    assert "'degraded-f'" in msgs and "not in the USAGE_KINDS" in msgs
    assert ("'degraded-frozen' has no KIND_PRIORITY rank" in msgs
            or "'degraded-frozen'" in msgs)


def test_obs005_uncataloged_bid_kind_fails(tmp_path):
    """A typo'd _bid() literal would raise ValueError on the first
    claim — the pass fails naming the kind, and 'idle' simultaneously
    loses its only claim site."""
    root = _obs5_root(tmp_path, mutate={
        obs_check.USAGE_PATH: lambda s: s.replace(
            'bids = [_bid("idle")]',
            'bids = [_bid("idlez")]')})
    findings = obs_check.run_usage(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS005" for (_, _, c, _) in findings)
    assert "'idlez'" in msgs and "would raise ValueError" in msgs
    assert "'idle'" in msgs and "never claimed by any _bid() site" in msgs


def test_obs005_non_literal_bid_kind_fails(tmp_path):
    """A computed kind at a _bid() site defeats the catalog closure even
    when it happens to be valid at runtime."""
    root = _obs5_root(tmp_path, mutate={
        obs_check.USAGE_PATH: lambda s: s.replace(
            '        bids.append(_bid("health-quarantine"))',
            '        bids.append(_bid(str("health-" + "quarantine")))')})
    findings = obs_check.run_usage(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS005" for (_, _, c, _) in findings)
    assert "string literal" in msgs
    assert ("'health-quarantine'" in msgs
            and "never claimed by any _bid() site" in msgs)


def test_obs005_hatched_catalog_kind_stays_silent(tmp_path):
    """`# obs: allow — <why>` on the catalog line is the escape hatch
    for kinds reserved ahead of their attribution site (the kind still
    needs a rank, or closure 1 fires)."""
    root = _obs5_root(tmp_path, mutate={
        obs_check.USAGE_PATH: lambda s: s.replace(
            '    "idle",',
            '    "ghost-kind",  # obs: allow — reserved for plugins\n'
            '    "idle",').replace(
            '    "idle": 0,',
            '    "ghost-kind": 0,\n    "idle": 0,')})
    assert obs_check.run_usage(root) == []


def test_obs005_emitted_family_without_help_fails(tmp_path):
    """A family the meter emits with no HELP_TEXTS entry is an
    unregistered metric (the OBS003 discipline, scoped to the usage
    prefix)."""
    root = _obs5_root(tmp_path, mutate={
        obs_check.USAGE_PATH: lambda s: s.replace(
            'USAGE_GAUGE_FAMILIES = ("usage_efficiency", '
            '"usage_capacity_nodes",',
            'USAGE_GAUGE_FAMILIES = ("usage_efficiency", '
            '"usage_phantom", "usage_capacity_nodes",')})
    findings = obs_check.run_usage(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS005" for (_, _, c, _) in findings)
    assert ("'tpu_operator_usage_phantom'" in msgs
            and "no HELP_TEXTS entry" in msgs)


def test_obs005_stale_usage_help_entry_fails(tmp_path):
    """A tpu_operator_usage_* HELP entry matching no emitted family is
    a stale registration (renamed or removed usage metric)."""
    root = _obs5_root(tmp_path, mutate={
        obs_check.METRICS_PATH: lambda s: s.replace(
            '    "tpu_operator_usage_seconds_total":',
            '    "tpu_operator_usage_ghost":\n'
            '        "stale help text for a family nothing emits",\n'
            '    "tpu_operator_usage_seconds_total":')})
    findings = obs_check.run_usage(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS005" for (_, _, c, _) in findings)
    assert ("'tpu_operator_usage_ghost'" in msgs
            and "matches no emitted family" in msgs)


def test_obs005_no_usage_module_skips(tmp_path):
    """A checkout without obs/usage.py must not fire at all — the
    closure needs the catalog side present."""
    root = _obs5_root(tmp_path, skip={obs_check.USAGE_PATH})
    assert obs_check.run_usage(root) == []


def test_obs005_catalog_gutted_is_parse_drift(tmp_path):
    """Renaming USAGE_KINDS away is parse drift, not a silent pass."""
    root = _obs5_root(tmp_path, mutate={
        obs_check.USAGE_PATH: lambda s: s.replace(
            "USAGE_KINDS = (", "USAGE_KINDZ = (")})
    findings = obs_check.run_usage(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "USAGE_KINDS catalog not found" in msgs


# ------------------------------------------------ CRS001 (scratch roots)

CRS_FILES = [crash_check.REGISTRY_PATH, crash_check.WIRE_PATH,
             "k8s_operator_libs_tpu/health/monitor.py",
             "k8s_operator_libs_tpu/health/remediation.py",
             "k8s_operator_libs_tpu/market/arbiter.py",
             "k8s_operator_libs_tpu/serving/router.py",
             "k8s_operator_libs_tpu/serving/pool.py"]


def _crs_root(tmp_path, mutate=None, skip=()):
    root = tmp_path / "repo_crs"
    for rel in CRS_FILES:
        if rel in skip:
            continue
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_crs001_real_repo_files_pass(tmp_path):
    assert crash_check.run_project(_crs_root(tmp_path)) == []


def test_crs001_real_repo_passes():
    assert crash_check.run_project(REPO) == []


def test_crs001_repo_without_crash_explorer_is_silent(tmp_path):
    assert crash_check.run_project(tmp_path) == []


def test_crs001_unregistered_stamp_fails(tmp_path):
    """A durable write stamping a wire key no site claims is an unswept
    crash boundary — the pass names the key and the stamping file."""
    root = _crs_root(tmp_path, mutate={
        "k8s_operator_libs_tpu/health/remediation.py": lambda s: s.replace(
            "annotations = {consts.QUARANTINE_REASON_ANNOTATION: reason,",
            "annotations = {consts.HEARTBEAT_ANNOTATION: \"0\",\n"
            "               consts.QUARANTINE_REASON_ANNOTATION: reason,")})
    findings = crash_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "CRS001" for (_, _, c, _) in findings)
    assert "HEARTBEAT_ANNOTATION" in msgs
    assert "unswept crash boundary" in msgs
    assert any(path.endswith("remediation.py")
               for (path, _, _, _) in findings)


def test_crs001_unknown_registry_claim_fails(tmp_path):
    root = _crs_root(tmp_path, mutate={
        crash_check.REGISTRY_PATH: lambda s: s.replace(
            '"health-verdict": ("VERDICT_LABEL",),',
            '"health-verdict": ("VERDICT_LABEL_X",),')})
    findings = crash_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "VERDICT_LABEL_X" in msgs and "not a wire.py constant" in msgs
    # the real key is now stamped-but-unclaimed, from the other side
    assert "VERDICT_LABEL " in msgs or "VERDICT_LABEL b" in msgs


def test_crs001_dead_coverage_fails(tmp_path):
    """A claim nothing stamps: registry rot that would quietly turn the
    sweep vacuous for that key."""
    root = _crs_root(tmp_path, mutate={
        crash_check.REGISTRY_PATH: lambda s: s.replace(
            '"health-verdict": ("VERDICT_LABEL",),',
            '"health-verdict": ("VERDICT_LABEL",\n'
            '                   "HEARTBEAT_ANNOTATION"),')})
    findings = crash_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "HEARTBEAT_ANNOTATION" in msgs
    assert "dead crash coverage" in msgs


def test_crs001_double_claim_fails(tmp_path):
    root = _crs_root(tmp_path, mutate={
        crash_check.REGISTRY_PATH: lambda s: s.replace(
            '"health-repair": ("REPAIR_ANNOTATION",',
            '"health-repair": ("VERDICT_LABEL", "REPAIR_ANNOTATION",')})
    findings = crash_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "claimed by BOTH" in msgs and "VERDICT_LABEL" in msgs


def test_crs001_missing_process_entry_fails(tmp_path):
    root = _crs_root(tmp_path, mutate={
        crash_check.REGISTRY_PATH: lambda s: s.replace(
            '    "health-verdict": "operator",\n', '')})
    findings = crash_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "health-verdict" in msgs and "SITE_PROCESS" in msgs


# --------------------------------------- EXC002 (package-shaped fixtures)

MONITOR_REL = "k8s_operator_libs_tpu/health/monitor.py"


def test_exc002_offender_fires_twice(tmp_path):
    """Both offender shapes: a broad catch with no hatch, and a hatch
    with no reason."""
    found = run_lint_pkg(tmp_path, exc_swallow.OFFENDERS["EXC002"],
                         "off_exc002.py")
    assert codes(found) == ["EXC002", "EXC002"], found
    msgs = " | ".join(found)
    assert "narrow to concrete types" in msgs
    assert "hatch without a reason" in msgs


def test_exc002_clean_stays_silent(tmp_path):
    found = run_lint_pkg(tmp_path, exc_swallow.CLEAN["EXC002"],
                         "clean_exc002.py")
    assert found == [], found


def test_exc002_out_of_scope_path_is_silent(tmp_path):
    """The same offender outside the package/cmd trees (e.g. a tools/
    script) is not EXC002's business."""
    found = run_lint(tmp_path, exc_swallow.OFFENDERS["EXC002"],
                     name="off_elsewhere.py")
    assert "EXC002" not in codes(found), found


def test_exc002_alternate_dash_spellings_accepted(tmp_path):
    src = (
        "def tick(mgr):\n"
        "    try:\n"
        "        mgr.apply_state()\n"
        "    except Exception:  # exc: allow -- double-dash reason\n"
        "        pass\n"
        "    try:\n"
        "        mgr.flush()\n"
        "    except Exception:  # exc: allow - single-dash reason\n"
        "        pass\n"
    )
    found = run_lint_pkg(tmp_path, src, "dashes.py")
    assert found == [], found


def test_exc002_real_package_is_triaged():
    """Satellite: the whole package + cmd trees carry ZERO unjustified
    broad catches — every survivor re-raises or carries a reasoned
    hatch. New broad catches must justify themselves at review time."""
    index = as_index(REPO)
    findings = []
    for rel in index.files_under("k8s_operator_libs_tpu") \
            + index.files_under("cmd"):
        findings.extend(lint.lint_file(REPO / rel))
    offenders = [f for f in findings if "EXC002" in f]
    assert offenders == [], offenders[:10]


# ----------------------------- dataflow engine scratch roots (EXC/STL)

# client.py rides along for the ApiError-family class hierarchy
# (is_subclass) — without it `except ApiError:` could not classify a
# ServerError escape
DFE_FILES = [MONITOR_REL, "k8s_operator_libs_tpu/core/client.py"]
KILL_FILES = DFE_FILES + [crash_check.REGISTRY_PATH, crash_check.WIRE_PATH]


def _dfe_root(tmp_path, mutate=None, files=DFE_FILES):
    root = tmp_path / "repo_dfe"
    for rel in files:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


# ------------------------------------------------ EXC001 (scratch roots)

def test_exc001_real_repo_passes():
    assert exc_contracts.run_project(REPO) == []


def test_exc001_monitor_root_passes(tmp_path):
    """The real monitor classifies ApiError at the tick boundary."""
    assert exc_contracts.run_project(_dfe_root(tmp_path)) == []


def test_exc001_family_raise_in_helper_fires_with_chain(tmp_path):
    """Inject a classified raise into a helper tick calls OUTSIDE the
    classified try: it escapes the tick boundary unclassified, and the
    finding renders the interprocedural chain."""
    root = _dfe_root(tmp_path, mutate={
        MONITOR_REL: lambda s: s.replace(
            "current = node.metadata.labels.get(consts.VERDICT_LABEL)",
            'raise ServerError("injected: verdict sync is down")')})
    findings = exc_contracts.run_project(root)
    assert findings and all(c == "EXC001" for (_, _, c, _) in findings)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "ServerError" in msgs
    assert "FleetHealthMonitor.tick -> " \
           "FleetHealthMonitor._sync_verdict_labels" in msgs
    assert "except ApiError" in msgs  # the prescribed fix
    assert all(p == MONITOR_REL for (p, _, _, _) in findings)


def test_exc001_renamed_root_is_config_drift(tmp_path):
    root = _dfe_root(tmp_path, mutate={
        MONITOR_REL: lambda s: s.replace("def tick(", "def tick_renamed(")})
    findings = exc_contracts.run_project(root)
    assert [(p, ln, c) for (p, ln, c, _) in findings] \
        == [(MONITOR_REL, 1, "EXC001")]
    assert "not found" in findings[0][3]


# ------------------------------------------------ EXC003 (scratch roots)

def test_exc003_real_repo_passes():
    assert exc_kill.run_project(REPO) == []


def test_exc003_broad_catch_over_durable_write_fires(tmp_path):
    """BaseException around the verdict patch would absorb the crash
    explorer's kill — the finding names the voided site."""
    root = _dfe_root(tmp_path, files=KILL_FILES, mutate={
        MONITOR_REL: lambda s: s.replace("except (ApiError, TimeoutError):",
                                         "except BaseException:")})
    findings = exc_kill.run_project(root)
    assert findings and all(c == "EXC003" for (_, _, c, _) in findings)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "health-verdict" in msgs and "OperatorKilled" in msgs


def test_exc003_reraise_passes(tmp_path):
    """A broad catch that re-raises is transparent to the kill."""
    root = _dfe_root(tmp_path, files=KILL_FILES, mutate={
        MONITOR_REL: lambda s: s.replace(
            "except (ApiError, TimeoutError):",
            "except BaseException:\n                raise\n"
            "            except (ApiError, TimeoutError):")})
    assert exc_kill.run_project(root) == []


def test_exc003_operator_killed_catch_site_exempt(tmp_path):
    """Naming OperatorKilled marks a designated campaign catch site."""
    root = _dfe_root(tmp_path, files=KILL_FILES, mutate={
        MONITOR_REL: lambda s: s.replace(
            "except (ApiError, TimeoutError):",
            "except (OperatorKilled, BaseException):")})
    assert exc_kill.run_project(root) == []


def test_exc003_hatch_suppresses(tmp_path):
    root = _dfe_root(tmp_path, files=KILL_FILES, mutate={
        MONITOR_REL: lambda s: s.replace(
            "except (ApiError, TimeoutError):",
            "except BaseException:  "
            "# exc: allow — deliberate last-ditch isolation")})
    assert exc_kill.run_project(root) == []


def test_exc003_repo_without_crash_explorer_is_silent(tmp_path):
    """No registry/wire in the checkout: nothing to void."""
    root = _dfe_root(tmp_path, mutate={
        MONITOR_REL: lambda s: s.replace("except (ApiError, TimeoutError):",
                                         "except BaseException:")})
    assert exc_kill.run_project(root) == []


# ------------------------------------------------ STL001 (scratch roots)

def test_stl001_real_repo_passes():
    assert stale_taint.run_project(REPO) == []


def test_stl001_monitor_root_passes(tmp_path):
    assert stale_taint.run_project(_dfe_root(tmp_path)) == []


def test_stl001_dropped_pump_fires(tmp_path):
    """Delete the tick-start pump: the store reads feeding the verdict
    patch are no longer freshness-barriered."""
    root = _dfe_root(tmp_path, mutate={
        MONITOR_REL: lambda s: s.replace(
            'pump(kinds=("Node", "Pod"))', "pass")})
    findings = stale_taint.run_project(root)
    assert findings and all(c == "STL001" for (_, _, c, _) in findings)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "patch_node_metadata" in msgs
    assert "freshness barrier" in msgs


def test_stl001_renamed_root_is_config_drift(tmp_path):
    root = _dfe_root(tmp_path, mutate={
        MONITOR_REL: lambda s: s.replace("def tick(", "def tick_renamed(")})
    findings = stale_taint.run_project(root)
    assert [(p, ln, c) for (p, ln, c, _) in findings] \
        == [(MONITOR_REL, 1, "STL001")]


# ------------------------------------------------- DataflowEngine units

def _mini_root(tmp_path, source):
    root = tmp_path / "repo_mini"
    f = root / "k8s_operator_libs_tpu" / "mini.py"
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(source)
    return root


def test_engine_scc_fixpoint_mutual_recursion(tmp_path):
    """a <-> b form one SCC; the bounded fixpoint propagates the raise
    to BOTH and terminates."""
    root = _mini_root(tmp_path, '''
def a(n):
    if n:
        return b(n - 1)
    raise ValueError("boom")


def b(n):
    return a(n)
''')
    engine = dataflow.get_engine(as_index(root))
    rel = "k8s_operator_libs_tpu/mini.py"
    assert "ValueError" in engine.summaries[(rel, "a")].raises
    # propagated across the cycle: b's witness is the call into a
    b_wit = engine.summaries[(rel, "b")].raises["ValueError"]
    assert b_wit[0] == "call" and b_wit[1] == (rel, "a")


def test_engine_client_alias_awareness(tmp_path):
    """`view = self._client` is an informer-store alias; the value of
    `self._client.direct()` is NOT (the uncached view cannot be
    stale)."""
    root = _mini_root(tmp_path, '''
class M:
    def helper(self):
        view = self._client
        cached = view.list_nodes()
        fresh = self._client.direct()
        uncached = fresh.list_nodes()
        return cached, uncached
''')
    engine = dataflow.get_engine(as_index(root))
    summary = engine.summaries[("k8s_operator_libs_tpu/mini.py",
                                "M.helper")]
    read_methods = [m for (_, m) in summary.reads]
    assert read_methods == ["list_nodes"], summary.reads
    # the RPC model: a client call may raise ServerError
    assert "ServerError" in summary.raises


def test_engine_cached_once_per_index(tmp_path):
    """get_engine builds once per ProjectIndex — the seam every pass
    shares. DataflowEngine.builds is the spy."""
    index = as_index(_mini_root(tmp_path, "def f():\n    pass\n"))
    before = dataflow.DataflowEngine.builds
    e1 = dataflow.get_engine(index)
    e2 = dataflow.get_engine(index)
    assert e1 is e2
    assert dataflow.DataflowEngine.builds == before + 1


def test_engine_chain_renders_propagation_path(tmp_path):
    root = _mini_root(tmp_path, '''
def outer(x):
    return inner(x)


def inner(x):
    raise RuntimeError("x")
''')
    engine = dataflow.get_engine(as_index(root))
    rel = "k8s_operator_libs_tpu/mini.py"
    chain = engine.chain((rel, "outer"), "RuntimeError", lattice="raises")
    assert "outer" in chain and "inner" in chain
    assert "RuntimeError" in chain


def test_engine_classified_handler_subtracts_only_named_family(tmp_path):
    """The dual-lattice contract: only an arm explicitly naming a
    CLASSIFIED family type subtracts the escape from `unclassified` —
    a blanket `except Exception` is a runtime catch, never a
    classification (name-based: without the client.py hierarchy it
    subtracts nothing from `raises` either)."""
    root = _mini_root(tmp_path, '''
def blanket(client):
    try:
        client.list_nodes()
    except Exception:
        pass


def named(client):
    try:
        client.list_nodes()
    except ServerError:
        pass
''')
    engine = dataflow.get_engine(as_index(root))
    rel = "k8s_operator_libs_tpu/mini.py"
    blanket = engine.summaries[(rel, "blanket")]
    assert "ServerError" in blanket.unclassified
    named = engine.summaries[(rel, "named")]
    assert "ServerError" not in named.raises
    assert "ServerError" not in named.unclassified


# --------------------------------------------------- --explain coverage

def test_every_registered_code_has_explain_entry():
    """Satellite contract: registering a code without a
    docs/static-analysis.md section is a test failure."""
    missing = [c for c in lint.all_codes() if not lint.explain(c)]
    assert missing == [], missing


def test_explain_cli_prints_docs_section():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--explain", "EXC001"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "exception-contract closure" in proc.stdout
    assert "exc_contracts.py" in proc.stdout


def test_explain_cli_unknown_code_exits_2():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--explain", "NOPE999"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 2


# -------------------------------------------------- runtime budget gate

def test_full_suite_inside_smoke_budget():
    """The interprocedural engine must not blow the make lint-smoke
    budget (LINT_BUDGET=60s): the FULL suite — generic + domain, engine
    build included — stays comfortably inside it in-process."""
    import time
    t0 = time.monotonic()
    findings, index = lint.run_suite(mode="all")
    elapsed = time.monotonic() - t0
    assert elapsed < 60, f"full suite took {elapsed:.1f}s"
    assert findings == [], findings[:5]
