"""tools/lint domain passes — JAX001–JAX004 jit-hygiene, LCK001–LCK003
lock discipline, STM001 state-machine exhaustiveness, ARC001 import
layering. Every code must fire on its module's offender fixture and stay
silent on the clean idiom; the cross-file passes are additionally proven
on mutated copies of the real repo files (delete a handler / add a fake
state → the pass fails naming exactly what is missing)."""

import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import lint  # noqa: E402  (the tools/lint package; shadows the shim)
from lint import (chaos_check, jax_hygiene, layering, lock_discipline,  # noqa: E402
                  obs_check, state_machine)
from lint.registry import REGISTRY  # noqa: E402


def run_lint(tmp_path, source, name="case.py"):
    f = tmp_path / name
    f.write_text(source)
    return lint.lint_file(f)


def codes(findings):
    return [f.split(": ")[1].split(" ")[0] for f in findings]


# --------------------------------------------------------------- registry

def test_registry_has_all_passes():
    names = {c.name for c in REGISTRY}
    assert {"generic", "jax-hygiene", "lock-discipline", "state-machine",
            "obs-journey", "obs-attribution", "obs-slo", "chaos-closure",
            "import-layering"} <= names
    all_codes = lint.all_codes()
    assert {"JAX001", "JAX002", "JAX003", "JAX004", "LCK001", "LCK002",
            "LCK003", "STM001", "OBS001", "OBS002", "OBS003", "CHS001",
            "ARC001"} <= set(all_codes)
    # codes are globally unique across checks
    per_check = [set(c.codes) for c in REGISTRY]
    assert sum(map(len, per_check)) == len(set().union(*per_check))


@pytest.mark.parametrize("mod", [jax_hygiene, lock_discipline])
def test_every_file_check_ships_fixture_pairs(mod):
    """The plugin contract: one firing offender and one silent clean
    fixture per code, carried by the check module itself."""
    assert set(mod.OFFENDERS) == set(mod.CODES)
    assert set(mod.CLEAN) == set(mod.CODES)


@pytest.mark.parametrize("mod", [jax_hygiene, lock_discipline])
def test_offender_fixtures_fire(mod, tmp_path):
    for code, src in mod.OFFENDERS.items():
        found = run_lint(tmp_path, src, name=f"off_{code}.py")
        assert code in codes(found), (code, found)


@pytest.mark.parametrize("mod", [jax_hygiene, lock_discipline])
def test_clean_fixtures_stay_silent(mod, tmp_path):
    for code, src in mod.CLEAN.items():
        found = run_lint(tmp_path, src, name=f"clean_{code}.py")
        assert found == [], (code, found)


# ------------------------------------------------------------ JAX hygiene

def test_jax_wrapper_returning_idiom_resolved(tmp_path):
    """`return jax.jit(train_step, ...)` over a local def (the
    parallel/fsdp.py / long_context.py idiom) marks the def as traced."""
    src = '''
import jax
import time

def make_train_step(optimizer):
    def train_step(state, tokens):
        t0 = time.time()
        return state, t0
    return jax.jit(train_step, donate_argnums=(0,))
'''
    found = run_lint(tmp_path, src)
    assert codes(found) == ["JAX001"] and "time.time" in found[0]


def test_jax_partial_alias_hop_resolved(tmp_path):
    """`kernel = partial(fn, ...)` then `pl.pallas_call(kernel, ...)`
    (the models/paged.py idiom) traces fn — through either arm of a
    conditional alias."""
    src = '''
import jax.experimental.pallas as pl
from functools import partial
import numpy as np

def _kernel_a(ref):
    return np.random.rand()

def _kernel_b(ref):
    return np.random.rand()

def dispatch(quant):
    if quant:
        kernel = partial(_kernel_a, n=1)
    else:
        kernel = partial(_kernel_b, n=1)
    return pl.pallas_call(kernel, grid=(1,))
'''
    found = run_lint(tmp_path, src)
    assert codes(found) == ["JAX002", "JAX002"]


def test_jax_static_argnames_exempt_from_host_sync(tmp_path):
    """float()/int() on a static_argnames parameter is concrete at trace
    time — silent; the same cast on a traced parameter fires."""
    src = '''
import jax
from functools import partial

@partial(jax.jit, static_argnames=("temperature",))
def sample(logits, temperature):
    scale = float(temperature)     # static: fine
    return logits * scale

@jax.jit
def bad(logits, temperature):
    return logits * float(temperature)   # traced: host sync
'''
    found = run_lint(tmp_path, src)
    assert codes(found) == ["JAX003"] and "temperature" in found[0]


def test_jax_shard_map_first_arg_traced(tmp_path):
    src = '''
import jax

def build(mesh, specs):
    def shard_gen(params, prompt):
        print("tracing", prompt.shape)
        return params
    return jax.shard_map(shard_gen, mesh=mesh, in_specs=specs,
                         out_specs=specs)
'''
    assert codes(run_lint(tmp_path, src)) == ["JAX001"]


def test_jax_nested_def_inherits_traced(tmp_path):
    src = '''
import jax
import random

@jax.jit
def outer(x):
    def body(carry, _):
        return carry + random.random(), None
    return jax.lax.scan(body, x, None, length=4)[0]
'''
    assert codes(run_lint(tmp_path, src)) == ["JAX002"]


def test_jax_item_call_fires(tmp_path):
    src = '''
import jax

@jax.jit
def step(x):
    return x.sum().item()
'''
    found = run_lint(tmp_path, src)
    assert codes(found) == ["JAX003"] and ".item()" in found[0]


def test_jax_suppression_hatch(tmp_path):
    src = '''
import jax
import time

@jax.jit
def step(x):
    t0 = time.time()  # lint: ignore
    return x + t0
'''
    assert run_lint(tmp_path, src) == []


# --------------------------------------------------------- lock discipline

def test_lck001_acquire_then_adjacent_try_finally_ok(tmp_path):
    src = lock_discipline.CLEAN["LCK001"]
    assert run_lint(tmp_path, src) == []


def test_lck001_message_names_receiver(tmp_path):
    found = run_lint(tmp_path, lock_discipline.OFFENDERS["LCK001"])
    assert "LOCK.acquire()" in found[0]


def test_lck002_nested_with_still_flagged(tmp_path):
    src = '''
import threading
import subprocess

class Refresher:
    def __init__(self):
        self._cache_lock = threading.Lock()

    def refresh(self):
        with self._cache_lock:
            if True:
                subprocess.check_output(["kubectl", "get", "nodes"])
'''
    found = run_lint(tmp_path, src)
    assert codes(found) == ["LCK002"] and "subprocess.check_output" in found[0]


def test_lck002_nested_function_deferred_not_flagged(tmp_path):
    src = '''
import threading
import time

class Scheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = []

    def schedule(self):
        with self._lock:
            def job():
                time.sleep(5)      # runs later, lock not held
            self.jobs.append(job)
'''
    assert run_lint(tmp_path, src) == []


def test_lck003_reports_unguarded_write_line(tmp_path):
    found = run_lint(tmp_path, lock_discipline.OFFENDERS["LCK003"])
    assert codes(found) == ["LCK003"]
    assert "self.draining" in found[0] and "_lock" in found[0]


def test_lck003_init_writes_exempt(tmp_path):
    src = lock_discipline.CLEAN["LCK003"]
    assert run_lint(tmp_path, src) == []


# ------------------------------------------- STM001 (cross-file, mutated)

STM_FILES = [state_machine.CONSTS_PATH, state_machine.STATE_PATH,
             state_machine.METRICS_PATH, state_machine.DIAGRAM_PATH]


def _stm_root(tmp_path, mutate=None):
    """Copy the real state-machine files into a scratch root, optionally
    mutating {relpath: fn(source) -> source}."""
    root = tmp_path / "repo"
    for rel in STM_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_stm001_real_repo_files_pass(tmp_path):
    assert state_machine.run_project(_stm_root(tmp_path)) == []


def test_stm001_deleted_handler_fails_naming_it(tmp_path):
    """Disabling process_drain_nodes must fail twice: the state loses its
    handler, and apply_state still calls the now-missing method."""
    root = _stm_root(tmp_path, mutate={
        state_machine.STATE_PATH: lambda s: s.replace(
            "def process_drain_nodes", "def _disabled_drain_nodes")})
    findings = state_machine.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings, "deleting a handler must fail the pass"
    assert "DRAIN_REQUIRED" in msgs and "no process_* handler" in msgs
    assert "process_drain_nodes" in msgs  # the dangling call site


def test_stm001_fake_state_fails_every_facet(tmp_path):
    root = _stm_root(tmp_path, mutate={
        state_machine.CONSTS_PATH: lambda s: s.replace(
            '    FAILED = "upgrade-failed"',
            '    FAILED = "upgrade-failed"\n    LIMBO = "limbo-required"')})
    findings = state_machine.run_project(root)
    msgs = [m for (_, _, _, m) in findings]
    assert any("LIMBO" in m and "no process_* handler" in m for m in msgs)
    assert any("LIMBO" in m and "UpgradeState.ALL" in m for m in msgs)
    assert any("LIMBO" in m and "metrics" in m for m in msgs)
    assert any("LIMBO" in m and "diagram" in m for m in msgs)


def test_stm001_state_dropped_from_all_is_caught(tmp_path):
    """ALL is the manually-maintained closure metrics iterate — a member
    silently removed from it must fail."""
    root = _stm_root(tmp_path, mutate={
        state_machine.CONSTS_PATH: lambda s: s.replace(
            "VALIDATION_REQUIRED, UNCORDON_REQUIRED, DONE, FAILED)",
            "VALIDATION_REQUIRED, UNCORDON_REQUIRED, DONE)")})
    findings = state_machine.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "FAILED" in msgs and "UpgradeState.ALL" in msgs


# ----------------------------------- STM001 health facet (cross-file, mutated)

HEALTH_FILES = STM_FILES + [state_machine.HEALTH_CONSTS_PATH,
                            state_machine.HEALTH_REMEDIATION_PATH,
                            state_machine.HEALTH_METRICS_PATH,
                            state_machine.HEALTH_DOC_PATH]


def _health_root(tmp_path, mutate=None):
    root = tmp_path / "repo"
    for rel in HEALTH_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_stm001_health_real_repo_files_pass(tmp_path):
    assert state_machine.run_project(_health_root(tmp_path)) == []


def test_stm001_health_facet_skipped_without_health_package(tmp_path):
    """Legacy fixture roots carrying only the upgrade machine still lint
    (the real repo always has health/consts.py)."""
    assert state_machine.run_project(_stm_root(tmp_path)) == []


def test_stm001_health_deleted_handler_entry_fails(tmp_path):
    """Removing a verdict's entry from the remediator's handlers() mapping
    must fail naming the verdict."""
    root = _health_root(tmp_path, mutate={
        state_machine.HEALTH_REMEDIATION_PATH: lambda s: s.replace(
            "            HealthVerdict.DEGRADED: self.process_degraded,\n",
            "")})
    findings = state_machine.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings, "deleting a handler entry must fail the pass"
    assert "DEGRADED" in msgs and "no handler entry" in msgs


def test_stm001_health_dangling_mapped_handler_fails(tmp_path):
    """A verdict mapped to a process_* method that no longer exists is the
    delete-the-method-not-the-mapping drift."""
    root = _health_root(tmp_path, mutate={
        state_machine.HEALTH_REMEDIATION_PATH: lambda s: s.replace(
            "def process_degraded", "def _disabled_degraded")})
    findings = state_machine.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "process_degraded" in msgs and "no such process_*" in msgs


def test_stm001_health_fake_verdict_fails_every_facet(tmp_path):
    root = _health_root(tmp_path, mutate={
        state_machine.HEALTH_CONSTS_PATH: lambda s: s.replace(
            '    UNHEALTHY_PERSISTENT = "unhealthy-persistent"',
            '    UNHEALTHY_PERSISTENT = "unhealthy-persistent"\n'
            '    LIMBO = "limbo-required"')})
    findings = state_machine.run_project(root)
    msgs = [m for (_, _, _, m) in findings]
    assert any("LIMBO" in m and "no handler entry" in m for m in msgs)
    assert any("LIMBO" in m and "HealthVerdict.ALL" in m for m in msgs)
    assert any("LIMBO" in m and "metrics" in m for m in msgs)
    assert any("LIMBO" in m and "fleet-health.md" in m for m in msgs)


def test_stm001_health_undocumented_verdict_fails(tmp_path):
    """Gutting docs/fleet-health.md must fail the doc facet for the
    verdicts whose wire value disappears."""
    root = _health_root(tmp_path, mutate={
        state_machine.HEALTH_DOC_PATH:
            lambda s: s.replace("unhealthy-persistent", "redacted")})
    findings = state_machine.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "UNHEALTHY_PERSISTENT" in msgs and "not documented" in msgs


# ------------------------------------------- OBS001 (cross-file, mutated)

OBS_FILES = [obs_check.CONSTS_PATH, obs_check.JOURNEY_PATH,
             obs_check.CHOKE_PATH]


def _obs_root(tmp_path, mutate=None, extra=None):
    """Copy the real journey/threshold/choke-point files into a scratch
    root, optionally mutating {relpath: fn(source) -> source} and adding
    {relpath: source} extras."""
    root = tmp_path / "repo"
    for rel in OBS_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    for rel, src in (extra or {}).items():
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_obs001_real_repo_files_pass(tmp_path):
    assert obs_check.run_project(_obs_root(tmp_path)) == []


def test_obs001_real_repo_passes():
    assert obs_check.run_project(REPO) == []


def test_obs001_missing_threshold_fails_naming_state(tmp_path):
    """Dropping one state's stuck-threshold default must fail naming the
    state (and flag the now-stale situation from neither side silently)."""
    root = _obs_root(tmp_path, mutate={
        obs_check.JOURNEY_PATH: lambda s: s.replace(
            '    "pod-restart-required": 900.0,\n', '')})
    findings = obs_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings, "a missing threshold must fail the pass"
    assert "POD_RESTART_REQUIRED" in msgs and "stuck-threshold" in msgs


def test_obs001_new_state_without_threshold_fails(tmp_path):
    root = _obs_root(tmp_path, mutate={
        obs_check.CONSTS_PATH: lambda s: s.replace(
            '    FAILED = "upgrade-failed"',
            '    FAILED = "upgrade-failed"\n    LIMBO = "limbo-required"')})
    findings = obs_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "LIMBO" in msgs and "stuck-threshold" in msgs


def test_obs001_stale_threshold_key_fails(tmp_path):
    """A threshold key no longer matching any wire value (renamed state)
    is dead configuration and must fail from the journey side."""
    root = _obs_root(tmp_path, mutate={
        obs_check.JOURNEY_PATH: lambda s: s.replace(
            '    "uncordon-required": 600.0,',
            '    "uncordon-required": 600.0,\n    "ghost-state": 60.0,')})
    findings = obs_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "ghost-state" in msgs and "no UpgradeState wire value" in msgs


ROGUE_STATE_WRITE = '''
class Sneaky:
    def __init__(self, client, keys):
        self._client = client
        self._keys = keys

    def force_done(self, name):
        self._client.patch_node_metadata(
            name, labels={self._keys.state_label: "upgrade-done"})
'''

ROGUE_JOURNEY_WRITE = '''
class Sneakier:
    def __init__(self, client, keys):
        self._client = client
        self._keys = keys

    def erase_history(self, name):
        self._client.patch_node_metadata(
            name, annotations={self._keys.journey_annotation: "[]"})
'''


def test_obs001_state_write_outside_choke_point_fires(tmp_path):
    root = _obs_root(tmp_path, extra={
        "k8s_operator_libs_tpu/health/rogue.py": ROGUE_STATE_WRITE})
    findings = obs_check.run_project(root)
    assert len(findings) == 1
    rel, _, code, msg = findings[0]
    assert code == "OBS001" and rel.endswith("health/rogue.py")
    assert "state-label key" in msg and "choke point" in msg


def test_obs001_journey_write_outside_choke_point_fires(tmp_path):
    root = _obs_root(tmp_path, extra={
        "cmd/rogue.py": ROGUE_JOURNEY_WRITE})
    findings = obs_check.run_project(root)
    assert len(findings) == 1
    assert "journey annotation" in findings[0][3]


def test_obs001_literal_key_write_fires_and_reads_stay_silent(tmp_path):
    """Spelling the key as a string literal instead of going through the
    KeyFactory is the sneakiest bypass; plain READS of the label never
    fire (cmd/status.py, health/monitor.py are full of them)."""
    root = _obs_root(tmp_path, extra={
        "k8s_operator_libs_tpu/tpu/rogue.py": (
            'def f(client, name):\n'
            '    client.patch_node_metadata(name, labels={\n'
            '        "tpu.dev/libtpu-driver-upgrade-state": "upgrade-done"'
            '})\n'),
        "k8s_operator_libs_tpu/tpu/reader.py": (
            'def g(node, keys):\n'
            '    return node.metadata.labels.get(keys.state_label)\n')})
    findings = obs_check.run_project(root)
    assert len(findings) == 1
    assert findings[0][0].endswith("tpu/rogue.py")


# ---------------------------------------- OBS002 (attribution, mutated)

OBS2_FILES = [obs_check.CONSTS_PATH, obs_check.ATTRIBUTION_PATH]


def _obs2_root(tmp_path, mutate=None):
    root = tmp_path / "repo2"
    for rel in OBS2_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_obs002_real_repo_files_pass(tmp_path):
    assert obs_check.run_attribution(_obs2_root(tmp_path)) == []


def test_obs002_real_repo_passes():
    assert obs_check.run_attribution(REPO) == []


def test_obs002_missing_phase_fails_naming_state(tmp_path):
    """Dropping a state's window-phase entry must fail naming the state
    — its dwell would silently leak out of attributed windows."""
    root = _obs2_root(tmp_path, mutate={
        obs_check.ATTRIBUTION_PATH: lambda s: s.replace(
            '    "pod-restart-required": "after_restart",\n', '')})
    findings = obs_check.run_attribution(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS002" for (_, _, c, _) in findings)
    assert "POD_RESTART_REQUIRED" in msgs and "window-phase" in msgs


def test_obs002_new_state_without_phase_fails(tmp_path):
    root = _obs2_root(tmp_path, mutate={
        obs_check.CONSTS_PATH: lambda s: s.replace(
            '    FAILED = "upgrade-failed"',
            '    FAILED = "upgrade-failed"\n'
            '    LIMBO = "limbo-required"')})
    findings = obs_check.run_attribution(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "LIMBO" in msgs and "window-phase" in msgs


def test_obs002_stale_key_fails(tmp_path):
    root = _obs2_root(tmp_path, mutate={
        obs_check.ATTRIBUTION_PATH: lambda s: s.replace(
            '    "upgrade-done": "outside",',
            '    "upgrade-done": "outside",\n'
            '    "ghost-state": "outside",')})
    findings = obs_check.run_attribution(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "ghost-state" in msgs and "no UpgradeState wire value" in msgs


def test_obs002_unknown_segment_name_fails(tmp_path):
    """A typo'd segment value would attribute dwell to a phase nothing
    reports — the whitelist catches it."""
    root = _obs2_root(tmp_path, mutate={
        obs_check.ATTRIBUTION_PATH: lambda s: s.replace(
            '    "drain-required": "gate_to_restart",',
            '    "drain-required": "gate_to_restrat",')})
    findings = obs_check.run_attribution(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "gate_to_restrat" in msgs and "not one of" in msgs


# ------------------------------------- OBS003 (SLO catalog, mutated)

OBS3_FILES = [obs_check.SLO_PATH, obs_check.ALERTS_PATH,
              obs_check.METRICS_PATH]


def _obs3_root(tmp_path, mutate=None):
    root = tmp_path / "repo3"
    for rel in OBS3_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_obs003_real_repo_files_pass(tmp_path):
    assert obs_check.run_slo(_obs3_root(tmp_path)) == []


def test_obs003_real_repo_passes():
    assert obs_check.run_slo(REPO) == []


def test_obs003_spec_with_unregistered_metric_fails(tmp_path):
    """A typo'd metric family in a default SLO spec would evaluate to
    "no data" forever — the pass fails naming the SLO and the family."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.SLO_PATH: lambda s: s.replace(
            '"metric": "tpu_operator_drain_duration_seconds"',
            '"metric": "tpu_operator_drain_duration_secondz"')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "OBS003" for (_, _, c, _) in findings)
    assert "drain-latency" in msgs
    assert "tpu_operator_drain_duration_secondz" in msgs


def test_obs003_emitted_family_without_help_fails(tmp_path):
    """A new emitted gauge family with no HELP_TEXTS entry would render
    with the underscores-to-spaces fallback."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.ALERTS_PATH: lambda s: s.replace(
            '    "tpu_operator_alert_firing",',
            '    "tpu_operator_alert_firing",\n'
            '    "tpu_operator_alert_pending",')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "tpu_operator_alert_pending" in msgs
    assert "no HELP_TEXTS entry" in msgs


def test_obs003_stale_help_entry_fails(tmp_path):
    """A tpu_operator_slo_* HELP entry nothing emits is a renamed or
    removed gauge seen from the catalog side."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.METRICS_PATH: lambda s: s.replace(
            '    "tpu_operator_alert_firing":',
            '    "tpu_operator_slo_ghost": "phantom budget gauge",\n'
            '    "tpu_operator_alert_firing":')})
    findings = obs_check.run_slo(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "tpu_operator_slo_ghost" in msgs
    assert "no emitted family" in msgs


def test_obs003_non_slo_help_entries_stay_exempt(tmp_path):
    """Only the slo/alert prefixes are closed over the emitted tables —
    the rest of the catalog (phase histograms, workload families) is
    owned by other layers and must not fire here."""
    root = _obs3_root(tmp_path, mutate={
        obs_check.METRICS_PATH: lambda s: s.replace(
            '    "tpu_operator_alert_firing":',
            '    "tpu_operator_some_new_histogram": "fine",\n'
            '    "tpu_operator_alert_firing":')})
    assert obs_check.run_slo(root) == []


# ------------------------------------- CHS001 (chaos catalog, mutated)

CHS_FILES = [chaos_check.FAULTS_PATH, chaos_check.SCENARIO_PATH,
             chaos_check.INVARIANTS_PATH]


def _chs_root(tmp_path, mutate=None):
    root = tmp_path / "repo_chs"
    for rel in CHS_FILES:
        src = (REPO / rel).read_text()
        if mutate and rel in mutate:
            src = mutate[rel](src)
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_chs001_real_repo_files_pass(tmp_path):
    assert chaos_check.run_project(_chs_root(tmp_path)) == []


def test_chs001_real_repo_passes():
    assert chaos_check.run_project(REPO) == []


def test_chs001_repo_without_chaos_package_is_silent(tmp_path):
    assert chaos_check.run_project(tmp_path) == []


def test_chs001_new_fault_without_parser_and_coverage_fails(tmp_path):
    """Adding a fault type the parsers/coverage don't know must fail
    naming the fault from BOTH directions."""
    root = _chs_root(tmp_path, mutate={
        chaos_check.FAULTS_PATH: lambda s: s.replace(
            '    "spot-reclaim",',
            '    "spot-reclaim",\n    "power-cut",')})
    findings = chaos_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert findings and all(c == "CHS001" for (_, _, c, _) in findings)
    assert "power-cut" in msgs
    assert "no scenario parser" in msgs
    assert "no FAULT_COVERAGE entry" in msgs


def test_chs001_dropped_parser_fails_naming_fault(tmp_path):
    root = _chs_root(tmp_path, mutate={
        chaos_check.SCENARIO_PATH: lambda s: s.replace(
            '    "watch-lag": _parse_watch_lag,\n', '')})
    findings = chaos_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "watch-lag" in msgs and "no scenario parser" in msgs


def test_chs001_stale_coverage_key_fails(tmp_path):
    root = _chs_root(tmp_path, mutate={
        chaos_check.INVARIANTS_PATH: lambda s: s.replace(
            '    "spot-reclaim": ("attribution", "event-dedup"),',
            '    "spot-reclaim": ("attribution", "event-dedup"),\n'
            '    "meteor-strike": ("budget",),')})
    findings = chaos_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "meteor-strike" in msgs and "no FAULT_TYPES member" in msgs


def test_chs001_unknown_invariant_name_fails(tmp_path):
    root = _chs_root(tmp_path, mutate={
        chaos_check.INVARIANTS_PATH: lambda s: s.replace(
            '"conflict-storm": ("budget", "journey"),',
            '"conflict-storm": ("budget", "vibes"),')})
    findings = chaos_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "vibes" in msgs and "unknown invariant" in msgs


def test_chs001_orphan_invariant_fails(tmp_path):
    """An invariant no fault stresses is a checker that rots silently."""
    root = _chs_root(tmp_path, mutate={
        chaos_check.INVARIANTS_PATH: lambda s: s.replace(
            '    "attribution",\n)',
            '    "attribution",\n    "entropy",\n)')})
    findings = chaos_check.run_project(root)
    msgs = " | ".join(m for (_, _, _, m) in findings)
    assert "entropy" in msgs and "stressed by no fault" in msgs


# ------------------------------------------------- ARC001 (fake packages)

ARC_LAYERS = {"utils": set(), "core": {"utils"}, "models": {"core"}}


def _arc_root(tmp_path, files):
    root = tmp_path / "arc"
    for rel, src in files.items():
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(src)
    return root


def test_arc001_clean_tree_silent(tmp_path):
    root = _arc_root(tmp_path, {
        "pkg/__init__.py": "from .models.m import M\n",
        "pkg/utils/__init__.py": "",
        "pkg/utils/u.py": "X = 1\n",
        "pkg/core/__init__.py": "",
        "pkg/core/c.py": "from ..utils.u import X\n",
        "pkg/models/__init__.py": "",
        "pkg/models/m.py": "from ..core.c import X\nM = X\n",
    })
    assert layering.run_project(root, package="pkg", layers=ARC_LAYERS) == []


def test_arc001_layer_violation_fires(tmp_path):
    root = _arc_root(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/core/__init__.py": "",
        "pkg/core/c.py": "from ..models.m import M\n",
        "pkg/models/__init__.py": "",
        "pkg/models/m.py": "M = 1\n",
        "pkg/utils/__init__.py": "",
    })
    findings = layering.run_project(root, package="pkg", layers=ARC_LAYERS)
    assert len(findings) == 1
    rel, lineno, code, msg = findings[0]
    assert code == "ARC001" and rel.endswith("core/c.py")
    assert "core may not import models" in msg


def test_arc001_cycle_fires_even_when_layer_legal(tmp_path):
    root = _arc_root(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/core/__init__.py": "",
        "pkg/core/a.py": "from .b import Y\nX = 1\n",
        "pkg/core/b.py": "from .a import X\nY = 2\n",
    })
    findings = layering.run_project(root, package="pkg", layers=ARC_LAYERS)
    assert len(findings) == 1
    assert "import cycle" in findings[0][3]
    assert "pkg.core.a" in findings[0][3] and "pkg.core.b" in findings[0][3]


def test_arc001_type_checking_imports_exempt(tmp_path):
    root = _arc_root(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/core/__init__.py": "",
        "pkg/core/c.py": ("from typing import TYPE_CHECKING\n"
                          "if TYPE_CHECKING:\n"
                          "    from ..models.m import M\n"),
        "pkg/models/__init__.py": "",
        "pkg/models/m.py": "M = 1\n",
    })
    assert layering.run_project(root, package="pkg", layers=ARC_LAYERS) == []


def test_arc001_real_repo_layers_match_declared_dag():
    assert layering.run_project(REPO) == []


# ------------------------------------------------------------- CLI surface

def test_python_m_tools_lint_domain_clean():
    out = subprocess.run([sys.executable, "-m", "tools.lint", "--domain"],
                         cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_shim_and_package_agree(tmp_path):
    """`python tools/lint.py <file>` (the historical entry) and the
    package produce identical findings."""
    f = tmp_path / "case.py"
    f.write_text(jax_hygiene.OFFENDERS["JAX001"])
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint.py"), str(f)],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 1
    assert [line for line in out.stdout.splitlines() if line] == \
        lint.lint_file(f)


def test_generic_mode_skips_domain_codes(tmp_path):
    f = tmp_path / "case.py"
    f.write_text(lock_discipline.OFFENDERS["LCK002"])
    assert lint.lint_file(f, domain=False) == []
    assert codes(lint.lint_file(f, domain=True)) == ["LCK002"]
