"""Seeded chaos harness: injector units, scenario parsing, campaign
e2e (converge under correlated faults + leader failover with zero
invariant violations), and the mutated-run fixtures proving every
invariant checker actually catches its violation class (docs/chaos.md).
"""

import pytest

from k8s_operator_libs_tpu.chaos import (FAULT_COVERAGE, FAULT_PARSERS,
                                         FAULT_TYPES, INVARIANT_NAMES,
                                         RECLAIM_DEADLINE_ANNOTATION,
                                         RECLAIM_TAINT_KEY, ChaosInjector,
                                         ScenarioError, parse_scenario,
                                         random_scenario)
from k8s_operator_libs_tpu.chaos.campaign import (run_scenario,
                                                  shrink_failure)
from k8s_operator_libs_tpu.chaos.faults import FaultEvent
from k8s_operator_libs_tpu.core.client import ConflictError, ServerError
from k8s_operator_libs_tpu.core.fakecluster import FakeCluster
from k8s_operator_libs_tpu.obs.billing import UsageLedger
from k8s_operator_libs_tpu.obs.goodput import read_ledger, split_runs
from k8s_operator_libs_tpu.obs.usage import USAGE_KINDS
from k8s_operator_libs_tpu.upgrade.util import KeyFactory
from k8s_operator_libs_tpu.utils.clock import FakeClock

KEYS = KeyFactory("libtpu")


# ------------------------------------------------------------- closure


def test_fault_catalog_closed_over_parsers_and_coverage():
    """The runtime mirror of CHS001: the three tables agree exactly."""
    assert set(FAULT_PARSERS) == set(FAULT_TYPES)
    assert set(FAULT_COVERAGE) == set(FAULT_TYPES)
    stressed = set()
    for invs in FAULT_COVERAGE.values():
        assert set(invs) <= set(INVARIANT_NAMES)
        stressed.update(invs)
    assert stressed == set(INVARIANT_NAMES), \
        "every invariant must be stressed by at least one fault"


# ------------------------------------------------------------ scenarios


def test_parse_scenario_resolves_slices_and_validates():
    sc = parse_scenario({
        "name": "x", "fleet": {"slices": 2, "hosts_per_slice": 2},
        "faults": [{"type": "driver-crashloop", "at": 10, "slices": [1]}]})
    assert sc.faults[0].targets == ["pool-1-h0", "pool-1-h1"]
    with pytest.raises(ScenarioError, match="unknown fault type"):
        parse_scenario({"faults": [{"type": "meteor-strike", "at": 0}]})
    with pytest.raises(ScenarioError, match="out of range"):
        parse_scenario({"faults": [{"type": "node-notready", "at": 0,
                                    "slices": [7]}]})
    with pytest.raises(ScenarioError, match="rate"):
        parse_scenario({"faults": [{"type": "apiserver-flake", "at": 0,
                                    "rate": 1.5}]})


def test_random_scenario_is_deterministic_per_seed():
    a, b = random_scenario(7), random_scenario(7)
    assert a.describe() == b.describe()
    assert random_scenario(8).describe() != a.describe()


# ------------------------------------------------------- injector units


def _mini_cluster():
    clock = FakeClock(100.0)
    cluster = FakeCluster(clock=clock)
    cluster.add_node("n0")
    cluster.add_pod("w0", "n0")
    return clock, cluster


def test_injector_flake_and_conflict_are_seeded_and_typed():
    clock, cluster = _mini_cluster()
    inj = ChaosInjector(cluster, clock, seed=3, events=[
        FaultEvent("apiserver-flake", at=0.0, duration=1e9,
                   params={"rate": 0.999}),
        FaultEvent("conflict-storm", at=0.0, duration=1e9,
                   params={"rate": 0.999}),
    ])
    inj.tick()
    client = inj.client("me")
    with pytest.raises((ServerError, ConflictError)):
        client.list_nodes()
    # reads flake with 5xx only; writes may draw either fault
    read_errors = set()
    for _ in range(20):
        try:
            client.get_node("n0")
        except Exception as exc:  # noqa: BLE001 - asserting the type set
            read_errors.add(type(exc))
    assert read_errors == {ServerError}
    # lease traffic is exempt from generic flake (leader election only
    # fails under a targeted leader-loss partition)
    with pytest.raises(KeyError):
        client.get_lease("ns", "missing")  # NotFound, never ServerError


def test_injector_latency_advances_injected_clock():
    clock, cluster = _mini_cluster()
    inj = ChaosInjector(cluster, clock, seed=5, events=[
        FaultEvent("apiserver-latency", at=0.0, duration=1e9,
                   params={"max_latency_s": 2.0})])
    inj.tick()
    t0 = clock.now()
    inj.client().list_nodes()
    inj.client().list_nodes()
    assert clock.now() > t0  # calls paid modelled latency


def test_injector_watch_lag_widens_and_restores_cache_lag():
    clock, cluster = _mini_cluster()
    base = cluster.cache_lag
    inj = ChaosInjector(cluster, clock, seed=1, events=[
        FaultEvent("watch-lag", at=0.0, duration=10.0,
                   params={"lag_s": 7.0})])
    inj.tick()
    assert cluster.cache_lag == 7.0
    clock.advance(11.0)
    inj.tick()
    assert cluster.cache_lag == base


def test_injector_reclaim_taints_then_heals():
    clock, cluster = _mini_cluster()
    inj = ChaosInjector(cluster, clock, seed=1, events=[
        FaultEvent("spot-reclaim", at=0.0, duration=30.0,
                   targets=["n0"], params={"deadline_s": 60.0})])
    inj.tick()
    node = cluster.client.direct().get_node("n0")
    assert any(t.key == RECLAIM_TAINT_KEY for t in node.spec.taints)
    assert RECLAIM_DEADLINE_ANNOTATION in node.metadata.annotations
    clock.advance(31.0)
    inj.tick()
    node = cluster.client.direct().get_node("n0")
    assert not any(t.key == RECLAIM_TAINT_KEY for t in node.spec.taints)
    assert RECLAIM_DEADLINE_ANNOTATION not in node.metadata.annotations


def test_injector_eviction_storm_registers_429s():
    clock, cluster = _mini_cluster()
    inj = ChaosInjector(cluster, clock, seed=1, events=[
        FaultEvent("eviction-storm", at=0.0, targets=["n0"],
                   params={"count": 2})])
    inj.tick()
    from k8s_operator_libs_tpu.core.client import TooManyRequestsError
    direct = cluster.client.direct()
    for _ in range(2):
        with pytest.raises(TooManyRequestsError):
            direct.evict_pod("default", "w0")
    direct.evict_pod("default", "w0")  # third attempt lands


# ----------------------------------------------------------- campaign


CORRELATED = {
    "name": "correlated-e2e",
    "max_ticks": 400,
    "fleet": {"slices": 2, "hosts_per_slice": 4, "solo_nodes": 1},
    "upgrade_at": 30.0,
    "faults": [
        {"type": "eviction-storm", "at": 45.0, "count": 3, "slices": [0]},
        {"type": "driver-crashloop", "at": 60.0, "duration": 90.0,
         "slices": [0, 1]},
        {"type": "leader-loss", "at": 150.0},
        {"type": "apiserver-flake", "at": 200.0, "duration": 90.0,
         "rate": 0.25},
    ],
}


def test_campaign_correlated_faults_converge_with_failover(tmp_path):
    """THE acceptance e2e: correlated two-slice crashloops, an eviction
    429 storm, a leader failover mid-phase, and an apiserver flake window
    — the fleet converges back to healthy/upgraded, the workload resumes,
    and every standing invariant holds on every tick."""
    res = run_scenario(parse_scenario(CORRELATED), seed=11,
                       workdir=str(tmp_path))
    assert res.violations == [], "\n".join(map(str, res.violations))
    assert res.converged, res.report()
    assert res.failovers >= 1, "leader-loss never drove a failover"
    # the simulated workload was preempted and resumed on ONE ledger
    records = read_ledger(str(tmp_path / "goodput.jsonl"))
    runs = split_runs(records)
    assert len(runs) >= 2
    assert any(r.get("kind") == "run_end" and r.get("preempted")
               for r in records)


def test_campaign_same_seed_same_trace(tmp_path):
    sc = parse_scenario(CORRELATED)
    r1 = run_scenario(sc, seed=4)
    r2 = run_scenario(sc, seed=4)
    assert r1.trace == r2.trace
    assert (r1.ticks, r1.failovers, r1.converged) == \
        (r2.ticks, r2.failovers, r2.converged)


def test_campaign_quick_seeds_converge():
    """A slice of the `make chaos` campaign pinned in CI: seeded-random
    scenarios converge with zero violations."""
    for seed in (0, 1):
        res = run_scenario(random_scenario(seed), seed)
        assert res.violations == [], res.report()
        assert res.converged, res.report()


# ------------------------------------- injected violations are CAUGHT


def _rogue_cordon_all(cluster=None, tick=None, **kw):
    if tick == 5:
        for n in cluster.client.direct().list_nodes():
            cluster.client.direct().patch_node_unschedulable(
                n.metadata.name, True)


def test_budget_invariant_catches_overcordon():
    sc = parse_scenario({"name": "rogue-budget", "max_ticks": 60,
                         "faults": []})
    res = run_scenario(sc, seed=0, hooks=[_rogue_cordon_all])
    assert res.failed
    assert any(v.invariant == "budget" for v in res.violations)
    assert "--base-seed 0" in res.report()  # replay line names the seed


def test_budget_exempts_cordons_of_fault_notready_nodes():
    """Regression pin (surfaced when the PR 15 fault catalog recomposed
    seed 20): the machine may admit + cordon a slice that the injector
    already holds NotReady — the reference's already-unavailable
    admission bypass, consuming no NEW availability — and the budget
    invariant must not charge the operator for it, during the fault
    window or after it heals mid-pipeline. The rogue-overcordon test
    above proves a GENUINE overdraw still fires."""
    sc = parse_scenario({
        "name": "notready-slice-admitted-free",
        "fleet": {"slices": 2, "hosts_per_slice": 4, "solo_nodes": 0},
        "max_unavailable": "50%", "upgrade_at": 75.0, "max_ticks": 600,
        "faults": [
            # the crowd keeps the router busy while slice 0 consumes the
            # whole budget; slice 1 then goes NotReady and is admitted
            # free — 8 nodes cordoned, 4 of them the injector's doing
            {"type": "flash-crowd", "at": 63.2, "duration": 180.0,
             "requestsPerTick": 6},
            {"type": "node-notready", "at": 128.8, "duration": 90.0,
             "slices": [1]},
        ]})
    res = run_scenario(sc, seed=20)
    assert res.converged and not res.violations, res.report()


def test_journey_invariant_catches_out_of_band_reset():
    wiped = []
    seen = []

    def rogue(cluster=None, keys=None, tick=None, **kw):
        if wiped:
            return
        node = cluster.client.direct().get_node("pool-0-h0")
        if node.metadata.annotations.get(keys.journey_annotation):
            if not seen:
                # let the checker observe the journey for one tick first
                seen.append(tick)
                return
            # a write bypassing the provider choke point wipes history
            cluster.client.direct().patch_node_metadata(
                "pool-0-h0", annotations={keys.journey_annotation: "[]"})
            wiped.append(tick)

    sc = parse_scenario({"name": "rogue-journey", "max_ticks": 80,
                         "faults": []})
    res = run_scenario(sc, seed=0, hooks=[rogue])
    assert wiped, "the rogue hook never found a journey to wipe"
    assert any(v.invariant == "journey" and "continuous" in v.detail
               for v in res.violations), res.report()


def test_event_dedup_invariant_catches_duplicate_stuck_events():
    def rogue(cluster=None, keys=None, tick=None, **kw):
        if tick == 10:
            node = cluster.client.direct().get_node("pool-0-h0")
            for _ in range(3):
                cluster.recorder.event(
                    node, "Warning", "StuckNode",
                    "Node pool-0-h0 stuck in cordon-required for 400s "
                    "(threshold 300s, component libtpu)")

    sc = parse_scenario({"name": "rogue-events", "max_ticks": 60,
                         "faults": []})
    res = run_scenario(sc, seed=0, hooks=[rogue])
    assert any(v.invariant == "event-dedup" for v in res.violations), \
        res.report()


def test_shrink_failure_minimizes_fault_schedule():
    """Delta-debugging: a scenario that fails regardless of which fault
    runs (tick budget too small to converge) shrinks to ONE fault."""
    sc = parse_scenario({
        "name": "shrink-me", "max_ticks": 3,
        "faults": [
            {"type": "apiserver-flake", "at": 5.0, "rate": 0.1},
            {"type": "node-notready", "at": 10.0, "slices": [0]},
            {"type": "leader-loss", "at": 15.0},
        ]})
    assert run_scenario(sc, seed=2).failed
    minimal = shrink_failure(sc, seed=2)
    assert len(minimal.faults) == 1
    assert run_scenario(minimal, seed=2).failed


# --------------------------------------------------- router-tier faults


def test_parse_router_faults_validate():
    sc = parse_scenario({
        "name": "rk", "fleet": {"slices": 2, "hosts_per_slice": 2},
        "faults": [
            {"type": "replica-kill", "at": 10, "duration": 60,
             "slices": [0]},
            {"type": "metrics-flake", "at": 20, "duration": 30,
             "slices": [0, 1]},
        ]})
    assert sc.faults[0].targets == ["pool-0-h0", "pool-0-h1"]
    assert sc.faults[1].targets == ["pool-0-h0", "pool-0-h1",
                                    "pool-1-h0", "pool-1-h1"]
    with pytest.raises(ScenarioError, match="duration"):
        parse_scenario({"faults": [{"type": "replica-kill", "at": 0,
                                    "duration": 0}]})
    with pytest.raises(ScenarioError, match="duration"):
        parse_scenario({"faults": [{"type": "metrics-flake", "at": 0,
                                    "duration": 0}]})


def test_parse_migration_faults_validate():
    sc = parse_scenario({
        "name": "mig", "fleet": {"slices": 2, "hosts_per_slice": 2},
        "faults": [
            {"type": "mid-stream-kill", "at": 10, "duration": 60,
             "slices": [1]},
            {"type": "kv-transfer-flake", "at": 20, "duration": 30,
             "rate": 0.4, "slices": [0]},
        ]})
    assert sc.faults[0].targets == ["pool-1-h0", "pool-1-h1"]
    assert sc.faults[1].params == {"rate": 0.4}
    with pytest.raises(ScenarioError, match="duration"):
        parse_scenario({"faults": [{"type": "mid-stream-kill", "at": 0,
                                    "duration": 0}]})
    with pytest.raises(ScenarioError, match="rate"):
        parse_scenario({"faults": [{"type": "kv-transfer-flake",
                                    "at": 0, "rate": 1.5}]})


def test_injector_migration_fault_windows():
    clock = FakeClock(1000.0)
    cluster = FakeCluster(clock=clock)
    inj = ChaosInjector(cluster, clock, seed=5, events=[
        FaultEvent("mid-stream-kill", at=10.0, duration=30.0,
                   targets=["n1"]),
        FaultEvent("kv-transfer-flake", at=10.0, duration=30.0,
                   targets=["n2"], params={"rate": 1.0 - 1e-9}),
    ])
    assert inj.mid_stream_kill_nodes() == set()
    assert not inj.kv_transfer_flaky("n2", "n3")
    clock.advance(15.0)
    inj.tick()
    assert inj.mid_stream_kill_nodes() == {"n1"}
    # rate ~1.0: every transfer touching n2 (either side) flakes
    assert inj.kv_transfer_flaky("n2", "n3")
    assert inj.kv_transfer_flaky("n3", "n2")
    assert not inj.kv_transfer_flaky("n4", "n5")
    clock.advance(30.0)
    inj.tick()
    assert inj.mid_stream_kill_nodes() == set()
    assert not inj.kv_transfer_flaky("n2", "n3")
    assert inj.quiet()


ROUTER_CHAOS = {
    "name": "router-faults-e2e",
    "max_ticks": 400,
    "fleet": {"slices": 2, "hosts_per_slice": 4, "solo_nodes": 0},
    "upgrade_at": 30.0,
    "faults": [
        {"type": "replica-kill", "at": 60.0, "duration": 90.0,
         "slices": [0]},
        {"type": "metrics-flake", "at": 75.0, "duration": 60.0,
         "slices": [0, 1]},
        {"type": "spot-reclaim", "at": 200.0, "duration": 120.0,
         "deadlineSeconds": 60.0, "slices": [1]},
    ],
}

# the migration acceptance scenario: a replica killed WITH streams in
# flight, the KV transfer path flaking through a reclaim-driven drain —
# the stream-integrity + exactly-once invariants must hold every tick
MIGRATION_CHAOS = {
    "name": "mid-stream-migration-e2e",
    "max_ticks": 400,
    "fleet": {"slices": 2, "hosts_per_slice": 4, "solo_nodes": 0},
    "upgrade_at": 30.0,
    "faults": [
        {"type": "mid-stream-kill", "at": 60.0, "duration": 90.0,
         "slices": [0]},
        {"type": "kv-transfer-flake", "at": 150.0, "duration": 120.0,
         "rate": 0.6, "slices": [0, 1]},
        {"type": "spot-reclaim", "at": 200.0, "duration": 120.0,
         "deadlineSeconds": 60.0, "slices": [1]},
    ],
}


def test_campaign_router_faults_converge_exactly_once(tmp_path):
    """Router-tier acceptance e2e: a replica process kill, a fleet-wide
    metrics-endpoint flake, and a reclaim of a serving slice — all while
    a rolling upgrade walks the fleet. The router invariants hold every
    tick (no request lost or double-served, admission never lands on a
    cordoned/quarantined/reclaimed slice), the killed replica's node
    hosts a fresh generation, and the fleet converges."""
    res = run_scenario(parse_scenario(ROUTER_CHAOS), seed=13,
                       workdir=str(tmp_path))
    assert res.violations == [], "\n".join(map(str, res.violations))
    assert res.converged, res.report()
    stats = res.router_stats
    assert stats["submitted"] > 0
    assert stats["completed"] == stats["submitted"], \
        "requests were lost across the faults"
    # the kill forced a respawn (a new generation beyond the initial 2)
    # and at least one drain rode the reclaim/upgrade
    assert stats["generations"] > 2
    assert stats["drains"] >= 1


def test_campaign_replica_kill_same_seed_same_router_stats(tmp_path):
    sc = parse_scenario(ROUTER_CHAOS)
    r1 = run_scenario(sc, seed=3)
    r2 = run_scenario(sc, seed=3)
    assert r1.router_stats == r2.router_stats
    assert r1.trace == r2.trace


def test_campaign_mid_stream_migration_holds_stream_integrity(tmp_path):
    """The migration acceptance e2e (ISSUE 12): replicas die WITH
    streaming requests in flight, the KV transfer path flakes while a
    reclaim drains a serving slice mid-rollout — and the campaign
    converges with the stream-integrity + exactly-once invariants
    holding every tick: no request lost, none double-served, every
    client stream gapless and token-identical to the deterministic
    decode, and every drain's in-flight work either live-migrated or
    degraded-not-lost."""
    res = run_scenario(parse_scenario(MIGRATION_CHAOS), seed=17,
                       workdir=str(tmp_path))
    assert res.violations == [], "\n".join(map(str, res.violations))
    assert res.converged, res.report()
    stats = res.router_stats
    assert stats["submitted"] > 0
    assert stats["completed"] == stats["submitted"], \
        "requests were lost across the migration faults"
    # drains live-migrated in-flight work (or degraded it, never lost):
    # the reclaim + rollout drains guarantee at least one migration
    assert stats["migrations"] + stats["migration_fallbacks"] >= 1
    assert stats["drains"] >= 1
    # the mid-stream kill forced a fresh generation
    assert stats["generations"] > 2


def test_campaign_migration_same_seed_same_stats(tmp_path):
    sc = parse_scenario(MIGRATION_CHAOS)
    r1 = run_scenario(sc, seed=23)
    r2 = run_scenario(sc, seed=23)
    assert r1.router_stats == r2.router_stats
    assert r1.trace == r2.trace


def _campaign_view_for(router, nodes):
    from k8s_operator_libs_tpu.chaos.invariants import CampaignView
    return CampaignView(tick=1, t=15.0, nodes=nodes, keys=KEYS,
                        budget=10, fault_notready=set(), leaders=["op-a"],
                        recorder_events=[], alert_status={},
                        router=router)


def test_parse_flash_crowd_validates():
    sc = parse_scenario({
        "name": "fc",
        "faults": [{"type": "flash-crowd", "at": 10, "duration": 60,
                    "requestsPerTick": 5}]})
    assert sc.faults[0].params == {"requests_per_tick": 5}
    assert sc.faults[0].targets == []       # traffic, not nodes
    with pytest.raises(ScenarioError, match="requestsPerTick"):
        parse_scenario({"faults": [{"type": "flash-crowd", "at": 0,
                                    "requestsPerTick": 0}]})
    with pytest.raises(ScenarioError, match="duration"):
        parse_scenario({"faults": [{"type": "flash-crowd", "at": 0,
                                    "duration": 0}]})


def test_injector_flash_crowd_rate_windows_sum():
    clock = FakeClock(1000.0)
    cluster = FakeCluster(clock=clock)
    inj = ChaosInjector(cluster, clock, seed=5, events=[
        FaultEvent("flash-crowd", at=10.0, duration=40.0,
                   params={"requests_per_tick": 7}),
        FaultEvent("flash-crowd", at=30.0, duration=40.0,
                   params={"requests_per_tick": 4}),
    ])
    assert inj.flash_crowd_rate() == 0
    clock.advance(15.0)
    assert inj.flash_crowd_rate() == 7
    clock.advance(20.0)         # both windows open
    assert inj.flash_crowd_rate() == 11
    clock.advance(20.0)         # first closed
    assert inj.flash_crowd_rate() == 4
    clock.advance(20.0)
    assert inj.flash_crowd_rate() == 0


# the ISSUE 13 composite acceptance scenario: a flash crowd landing
# DURING a rolling upgrade DURING a spot reclaim — the capacity market
# trades the training node to serving at the peak and returns it after
# the trough, with every standing invariant (budget, single-leader,
# exactly-once, stream-integrity, attribution, market-conservation) green
MARKET_CHAOS = {
    "name": "flash-crowd-market-e2e",
    "max_ticks": 400,
    "fleet": {"slices": 2, "hosts_per_slice": 4, "solo_nodes": 0},
    "upgrade_at": 30.0,
    "faults": [
        {"type": "flash-crowd", "at": 45.0, "duration": 600.0,
         "requestsPerTick": 25},
        {"type": "spot-reclaim", "at": 90.0, "duration": 120.0,
         "deadlineSeconds": 60.0, "slices": [1]},
    ],
}


def test_campaign_flash_crowd_market_trade_converges(tmp_path):
    """ACCEPTANCE (ISSUE 13): the composite scenario converges with
    zero violations, the arbiter traded the training slice and returned
    it, overload shed only the sheddable lanes, and the workload's
    ledger shows the market preemption as a priced drain-save exit with
    a later resume — never a lost request, never a broken stream."""
    res = run_scenario(parse_scenario(MARKET_CHAOS), seed=29,
                       workdir=str(tmp_path))
    assert res.violations == [], "\n".join(map(str, res.violations))
    assert res.converged, res.report()
    stats = res.router_stats
    assert stats["market_trades"] >= 1, "the flash crowd never traded"
    assert stats["market_returns"] == stats["market_trades"], \
        "a traded slice was never returned"
    # exactly-once through the overload: everything accepted is either
    # delivered or explicitly shed, and only sheddable lanes shed
    assert stats["completed"] + stats["shed"] == stats["submitted"]
    assert stats["shed"] > 0, "a 10-req/tick crowd should have shed"
    # the training job was preempted by the trade and resumed after the
    # return, continuing ONE ledger
    records = read_ledger(str(tmp_path / "goodput.jsonl"))
    assert any(r.get("kind") == "run_end" and r.get("preempted")
               for r in records)
    assert len(split_runs(records)) >= 2


def test_campaign_market_replay_is_byte_deterministic(tmp_path):
    sc = parse_scenario(MARKET_CHAOS)
    r1 = run_scenario(sc, seed=31)
    r2 = run_scenario(sc, seed=31)
    assert r1.trace == r2.trace
    assert r1.router_stats == r2.router_stats
    assert (r1.ticks, r1.failovers, r1.converged) == \
        (r2.ticks, r2.failovers, r2.converged)


class _StubMarket:
    def __init__(self, entries):
        self.entries = entries

    def ownership(self):
        return self.entries


def test_market_conservation_invariant_fires():
    from k8s_operator_libs_tpu.chaos.invariants import (
        MarketConservationInvariant)
    from k8s_operator_libs_tpu.wire import MARKET_OWNER_LABEL
    clock = FakeClock()
    cluster = FakeCluster(clock=clock)
    for name in ("m0", "m1", "x0", "x1"):
        cluster.add_node(name)

    def view(market, budget=10):
        from k8s_operator_libs_tpu.chaos.invariants import CampaignView
        nodes = {n.metadata.name: n
                 for n in cluster.client.direct().list_nodes()}
        return CampaignView(tick=1, t=15.0, nodes=nodes, keys=KEYS,
                            budget=budget, fault_notready=set(),
                            leaders=["op-a"], recorder_events=[],
                            alert_status={}, market=market)

    ok = _StubMarket([{"slice": "s0", "owner": "training",
                       "phase": "training", "nodes": ["m0", "m1"],
                       "stamp_pending": False}])
    assert MarketConservationInvariant().check(view(ok)) == []
    # unknown owner value
    bad = _StubMarket([{"slice": "s0", "owner": "pirate",
                        "phase": "pirate", "nodes": ["m0"],
                        "stamp_pending": False}])
    out = MarketConservationInvariant().check(view(bad))
    assert any("unknown party" in v.detail for v in out)
    # one node claimed by two slices
    dup = _StubMarket([
        {"slice": "s0", "owner": "training", "phase": "training",
         "nodes": ["m0"], "stamp_pending": False},
        {"slice": "s1", "owner": "serving", "phase": "serving",
         "nodes": ["m0"], "stamp_pending": False}])
    out = MarketConservationInvariant().check(view(dup))
    assert any("claimed by managed slices" in v.detail for v in out)
    # split owner labels on one settled slice
    cluster.client.direct().patch_node_metadata(
        "m0", labels={MARKET_OWNER_LABEL: "training"})
    cluster.client.direct().patch_node_metadata(
        "m1", labels={MARKET_OWNER_LABEL: "serving"})
    out = MarketConservationInvariant().check(view(ok))
    assert any("split trade" in v.detail for v in out)
    # budget: a trade INITIATED while the operator holds the budget
    cluster.client.direct().patch_node_unschedulable("x0", True)
    cluster.client.direct().patch_node_unschedulable("x1", True)
    trading = _StubMarket([{"slice": "s0", "owner": "draining",
                            "phase": "preempting", "nodes": ["m0"],
                            "stamp_pending": True}])
    out = MarketConservationInvariant().check(view(trading, budget=2))
    assert any("maxUnavailable budget" in v.detail for v in out)
    # steady state after initiation is NOT re-charged
    inv = MarketConservationInvariant()
    cluster.client.direct().patch_node_unschedulable("x0", False)
    cluster.client.direct().patch_node_unschedulable("x1", False)
    assert inv.check(view(trading, budget=2)) == []   # initiation fits
    cluster.client.direct().patch_node_unschedulable("x0", True)
    cluster.client.direct().patch_node_unschedulable("x1", True)
    assert [v for v in inv.check(view(trading, budget=2))
            if "budget" in v.detail] == []


def test_router_exactly_once_invariant_catches_double_serve():
    from k8s_operator_libs_tpu.chaos.invariants import (
        RouterExactlyOnceInvariant)
    from k8s_operator_libs_tpu.serving import (Replica, ReplicaPool,
                                               RequestRouter,
                                               SimReplicaRuntime)
    pool = ReplicaPool(component="libtpu", clock=FakeClock())
    pool.register(Replica("a", "node-a", SimReplicaRuntime()))
    router = RequestRouter(pool, clock=FakeClock())
    rid = router.submit([1, 2], 2)
    inv = RouterExactlyOnceInvariant()
    assert inv.check(_campaign_view_for(router, {})) == []
    # a rogue duplicate delivery must be flagged the tick it appears
    router.completed_counts[rid] = 2
    out = inv.check(_campaign_view_for(router, {}))
    assert len(out) == 1 and "delivered 2 times" in out[0].detail
    # and a request stranded on a dead replica is a loss
    router.completed_counts[rid] = 1
    pool.replicas["a"].failed = True
    out = inv.check(_campaign_view_for(router, {}))
    assert any("dead replica" in v.detail for v in out)


def test_router_admission_invariant_catches_cordoned_placement():
    from k8s_operator_libs_tpu.chaos.invariants import (
        RouterAdmissionInvariant)
    from k8s_operator_libs_tpu.serving import (Replica, ReplicaPool,
                                               RequestRouter,
                                               SimReplicaRuntime)
    clock = FakeClock()
    cluster = FakeCluster(clock=clock)
    cluster.add_node("node-a")
    pool = ReplicaPool(component="libtpu", clock=clock,
                       client=cluster.client)
    pool.register(Replica("a", "node-a", SimReplicaRuntime()))
    router = RequestRouter(pool, clock=clock)
    router.submit([1], 2)
    nodes = {n.metadata.name: n
             for n in cluster.client.direct().list_nodes()}
    inv = RouterAdmissionInvariant()
    assert inv.check(_campaign_view_for(router, nodes)) == []
    # rogue: the node was cordoned, yet an assignment targeted it
    cluster.client.direct().patch_node_unschedulable("node-a", True)
    nodes = {n.metadata.name: n
             for n in cluster.client.direct().list_nodes()}
    out = inv.check(_campaign_view_for(router, nodes))
    assert len(out) == 1 and "CORDONED" in out[0].detail


# ------------------------------------------------- fleet usage ledger

# the ISSUE 20 composite acceptance scenario: a flash crowd DURING a
# rolling upgrade DURING a spot reclaim DURING an apiserver blackout —
# four correlated faults, and still every slice-second of capacity lands
# in exactly one usage bucket, with the blackout's frozen ticks billed
# as degraded-frozen, never laundered into idle
USAGE_CHAOS = {
    "name": "usage-conservation-composite",
    "max_ticks": 500,
    "fleet": {"slices": 2, "hosts_per_slice": 4, "solo_nodes": 0},
    "upgrade_at": 30.0,
    "faults": [
        {"type": "flash-crowd", "at": 45.0, "duration": 400.0,
         "requestsPerTick": 25},
        {"type": "spot-reclaim", "at": 90.0, "duration": 120.0,
         "deadlineSeconds": 60.0, "slices": [1]},
        {"type": "apiserver-blackout", "at": 150.0, "duration": 90.0},
    ],
}


def test_campaign_composite_usage_conservation(tmp_path):
    """ACCEPTANCE (ISSUE 20): the composite scenario converges with the
    usage-conservation invariant (and every older one) green, the
    shared ledger accounts capacity through the blackout's fail-static
    freeze, and the frozen ticks are attributed as degraded-frozen."""
    res = run_scenario(parse_scenario(USAGE_CHAOS), seed=29,
                       workdir=str(tmp_path))
    assert res.violations == [], "\n".join(map(str, res.violations))
    assert res.converged, res.report()
    assert res.usage_records > 0 and res.usage_digest
    records = [r for r in UsageLedger(
        str(tmp_path / "usage.jsonl")).read() if r.get("kind") == "usage"]
    assert len(records) == res.usage_records
    # conservation, re-checked here record by record (the invariant
    # already replayed these during the run — this is the belt)
    for rec in records:
        claimed = sum(int(n) for lanes in rec["counts"].values()
                      for n in lanes.values())
        assert claimed == rec["nodes"], rec
        assert set(rec["counts"]) <= set(USAGE_KINDS)
    # the blackout froze the operator: its ticks bill as degraded-frozen
    degraded = [r for r in records if r["degraded"]]
    assert degraded, "the blackout never produced a degraded tick"
    for rec in degraded:
        assert set(rec["counts"]) == {"degraded-frozen"}, rec
    # cumulative capacity is monotone across the failovers the blackout
    # induced — the ledger-tail resume held
    cums = [r["cum"]["capacity_s"] for r in records]
    assert cums == sorted(cums)
    assert cums[-1] > 0
    # the account saw productive AND waste kinds (the upgrade and the
    # reclaim both ran), so the efficiency headline means something
    kinds_seen = set()
    for rec in records:
        kinds_seen.update(k for k, lanes in rec["counts"].items()
                          if any(lanes.values()))
    assert "serving" in kinds_seen or "training" in kinds_seen
    assert "upgrade-maintenance" in kinds_seen


def test_campaign_usage_ledger_replay_is_byte_identical():
    """Same seed, same scenario → byte-identical usage ledgers (the
    acceptance digest check: billing is deterministic end to end)."""
    sc = parse_scenario(USAGE_CHAOS)
    r1 = run_scenario(sc, seed=31)
    r2 = run_scenario(sc, seed=31)
    assert r1.usage_digest is not None
    assert r1.usage_digest == r2.usage_digest
    assert r1.usage_records == r2.usage_records


def test_usage_conservation_invariant_fires(tmp_path):
    """Hand-written rogue ledgers: every violation class the checker
    promises to catch, caught at the record it appears — and only
    once (the stateful replay cursor)."""
    from k8s_operator_libs_tpu.chaos.invariants import (
        CampaignView, UsageConservationInvariant)
    from k8s_operator_libs_tpu.obs.billing import UsageLedger as Ledger
    path = str(tmp_path / "usage.jsonl")

    def view():
        return CampaignView(tick=1, t=15.0, nodes={}, keys=KEYS,
                            budget=10, fault_notready=set(),
                            leaders=["op-a"], recorder_events=[],
                            alert_status={}, usage_ledger_path=path)

    ledger = Ledger(path)
    inv = UsageConservationInvariant()
    assert inv.check(view()) == []          # empty ledger: green
    ledger.append({"kind": "usage", "tick": 1, "t": 10.0,
                   "elapsed_s": 1.0, "nodes": 4, "capacity_s": 4.0,
                   "degraded": False, "counts": {"idle": {"-": 4}},
                   "cum": {"capacity_s": 4.0, "ticks": 1}})
    assert inv.check(view()) == []          # a clean record: green
    # under-claim: 4 nodes, 3 attributed
    ledger.append({"kind": "usage", "tick": 2, "t": 11.0,
                   "elapsed_s": 1.0, "nodes": 4, "capacity_s": 4.0,
                   "degraded": False, "counts": {"idle": {"-": 3}},
                   "cum": {"capacity_s": 8.0, "ticks": 2}})
    out = inv.check(view())
    assert len(out) == 1 and "conservation broken" in out[0].detail
    assert inv.check(view()) == []          # replayed once, not twice
    # unknown kind + capacity != nodes x elapsed
    ledger.append({"kind": "usage", "tick": 3, "t": 12.0,
                   "elapsed_s": 1.0, "nodes": 2, "capacity_s": 9.0,
                   "degraded": False, "counts": {"napping": {"-": 2}},
                   "cum": {"capacity_s": 17.0, "ticks": 3}})
    out = inv.check(view())
    details = " | ".join(v.detail for v in out)
    assert "unknown kind(s) ['napping']" in details
    assert "!= nodes × elapsed" in details
    # a DEGRADED tick that launders frozen capacity into idle
    ledger.append({"kind": "usage", "tick": 4, "t": 13.0,
                   "elapsed_s": 1.0, "nodes": 4, "capacity_s": 4.0,
                   "degraded": True,
                   "counts": {"degraded-frozen": {"-": 2},
                              "idle": {"-": 2}},
                   "cum": {"capacity_s": 21.0, "ticks": 4}})
    out = inv.check(view())
    assert len(out) == 1 and "never idle" in out[0].detail
    # cumulative capacity regression: the resume-from-tail was lost
    ledger.append({"kind": "usage", "tick": 5, "t": 14.0,
                   "elapsed_s": 1.0, "nodes": 4, "capacity_s": 4.0,
                   "degraded": False, "counts": {"idle": {"-": 4}},
                   "cum": {"capacity_s": 4.0, "ticks": 1}})
    out = inv.check(view())
    assert len(out) == 1 and "regressed" in out[0].detail
    assert "resume lost across" in out[0].detail
