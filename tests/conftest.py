"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh (multi-chip shardings are
validated without TPU hardware, per the driver's dryrun contract). These env
vars must be set before jax is first imported anywhere in the test process.
"""

import os

# The image pins JAX_PLATFORMS=axon (one real TPU chip via tunnel) and
# pre-imports jax from sitecustomize, so plain env overwrites are too late —
# jax.config is the reliable switch. Tests run on the 8-device virtual CPU
# mesh to validate multi-chip shardings without hardware.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from k8s_operator_libs_tpu.core.fakecluster import FakeCluster  # noqa: E402
from k8s_operator_libs_tpu.upgrade.util import KeyFactory  # noqa: E402
from k8s_operator_libs_tpu.utils.clock import FakeClock  # noqa: E402


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def cluster(clock):
    """An envtest-equivalent cluster with a small but nonzero cache lag, so
    the cache-sync barrier is actually exercised (reference
    node_upgrade_state_provider.go:92-117)."""
    return FakeCluster(clock=clock, cache_lag=0.5)


@pytest.fixture
def keys():
    return KeyFactory("gpu")
