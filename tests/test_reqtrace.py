"""obs/reqtrace.py — the request flight recorder.

Acceptance bars pinned here (ISSUE 18):

- stage timelines PARTITION the request's measured latency (the
  sums-to-the-window law from obs/attribution.py, by construction);
- the trace context survives encode/parse round-trips and every
  garbled form degrades to None (fresh root trace, never an error);
- tracing is provably free: the chaos campaign behaves IDENTICALLY
  (router stats, per-tick sim token streams, invariants, fault trace)
  with the recorder on and off on the same seed, and the same seed
  replays byte-identical timelines twice;
- the request-trace-integrity invariant actually catches corruption:
  illegal transitions, terminal-in-open timelines, and recorder/router
  migration-ledger mismatches all produce violations;
- memory is fixed: the closed ring and the open table are bounded and
  the eviction counters stay truthful.
"""

import json

import pytest

from k8s_operator_libs_tpu.chaos.campaign import run_scenario
from k8s_operator_libs_tpu.chaos.invariants import (
    CampaignView, RequestTraceIntegrityInvariant)
from k8s_operator_libs_tpu.chaos.scenario import parse_scenario
from k8s_operator_libs_tpu.obs.metrics import MetricsHub
from k8s_operator_libs_tpu.obs.reqtrace import (
    LEGAL_STAGE_TRANSITIONS, MIGRATION_STAGES, STAGES, TERMINAL_STAGES,
    RequestTraceRecorder, TraceContext, durations_partition_latency,
    parse_trace_header, stage_durations, validate_timeline)
from k8s_operator_libs_tpu.utils.clock import FakeClock


# ------------------------------------------------------------ wire format


def test_trace_context_roundtrip():
    ctx = TraceContext(trace_id="t00000001", span_id="s000001", hop=2)
    parsed = parse_trace_header(ctx.encode())
    assert parsed == ctx


@pytest.mark.parametrize("garbled", [
    None, "", "   ", "t1/s1", "t1/s1/2/9", "t1//0", "/s1/0",
    "t1/s1/x", "t1/s1/-1", "t1/s1/1000", "t~1/s1/0", "t1/s 1/0",
    "x" * 65 + "/s1/0", "t1/s1/0/",
])
def test_parse_trace_header_garbled_degrades_to_none(garbled):
    """A dropped or corrupted X-TPU-Trace header must yield None — the
    caller mints a fresh root trace and serves the request anyway."""
    assert parse_trace_header(garbled) is None


def test_stage_catalog_closed_over_transitions():
    """Every stage appears in the transition table, every successor is a
    known stage, and terminals have no successors."""
    assert set(LEGAL_STAGE_TRANSITIONS) == set(STAGES)
    for stage, nxt in LEGAL_STAGE_TRANSITIONS.items():
        assert set(nxt) <= set(STAGES), stage
        if stage in TERMINAL_STAGES:
            assert nxt == ()


# ------------------------------------------------- the partition law


def test_stage_durations_partition_latency():
    stages = [(0, "admitted", 10.0), (1, "queued", 10.5),
              (2, "assigned", 12.0), (3, "prefill", 12.0),
              (4, "first_token", 14.5), (5, "streaming", 14.5),
              (6, "completed", 20.0)]
    durations = stage_durations(stages)
    assert durations["admitted"] == pytest.approx(0.5)
    assert durations["queued"] == pytest.approx(1.5)
    assert durations["prefill"] == pytest.approx(2.5)
    assert durations["streaming"] == pytest.approx(5.5)
    assert "completed" not in durations    # terminal dwells zero
    assert sum(durations.values()) == pytest.approx(10.0)
    assert durations_partition_latency({"stages": stages})


def test_stage_durations_accumulate_revisits():
    """A crash requeue visits queued twice — both dwells count, and the
    telescoping sum still equals the window."""
    stages = [(0, "admitted", 0.0), (1, "queued", 1.0),
              (2, "assigned", 3.0), (3, "prefill", 3.0),
              (4, "queued", 5.0), (5, "assigned", 9.0),
              (6, "prefill", 9.0), (7, "completed", 12.0)]
    durations = stage_durations(stages)
    assert durations["queued"] == pytest.approx(2.0 + 4.0)
    assert sum(durations.values()) == pytest.approx(12.0)


def test_validate_timeline_flags_each_defect():
    ok = {"rid": 1, "stages": [[0, "admitted", 0.0], [1, "queued", 1.0],
                               [2, "shed", 2.0]]}
    assert validate_timeline(ok, closed=True) == []
    bad_start = {"rid": 2, "stages": [[0, "queued", 0.0],
                                      [1, "shed", 1.0]]}
    assert any("not 'admitted'" in m
               for m in validate_timeline(bad_start))
    illegal = {"rid": 3, "stages": [[0, "admitted", 0.0],
                                    [1, "streaming", 1.0],
                                    [2, "completed", 2.0]]}
    assert any("illegal stage transition" in m
               for m in validate_timeline(illegal))
    gap = {"rid": 4, "stages": [[0, "admitted", 0.0], [2, "queued", 1.0],
                                [3, "shed", 2.0]]}
    assert any("gap or duplicate" in m for m in validate_timeline(gap))
    regress = {"rid": 5, "stages": [[0, "admitted", 5.0],
                                    [1, "queued", 4.0],
                                    [2, "shed", 6.0]]}
    assert any("regressed" in m for m in validate_timeline(regress))
    open_terminal = {"rid": 6, "stages": [[0, "admitted", 0.0],
                                          [1, "queued", 1.0],
                                          [2, "shed", 2.0]]}
    assert any("open timeline" in m
               for m in validate_timeline(open_terminal, closed=False))
    not_closed = {"rid": 7, "stages": [[0, "admitted", 0.0],
                                       [1, "queued", 1.0]]}
    assert any("non-terminal" in m for m in validate_timeline(not_closed))
    lying = dict(ok, durations={"admitted": 40.0}, latency_s=2.0)
    assert any("attribution law" in m for m in validate_timeline(lying))


# ------------------------------------------------------------- recorder


def test_recorder_happy_path_closes_and_observes():
    clock = FakeClock(100.0)
    hub = MetricsHub()
    rec = RequestTraceRecorder(clock=clock, metrics=hub)
    ctx = rec.begin(1, lane="interactive")
    assert ctx.hop == 0 and ctx.trace_id.startswith("t")
    rec.stage(1, "queued")
    clock.advance(2.0)
    rec.stage(1, "assigned")
    rec.stage(1, "prefill")
    clock.advance(1.0)
    rec.token_appended(1)          # prefill -> first_token -> streaming
    clock.advance(3.0)
    rec.token_appended(1)          # already streaming: no-op
    rec.stage(1, "completed")
    assert rec.open_count() == 0 and rec.closed == 1
    timeline = rec.timeline(1)
    assert [s for _, s, _ in timeline["stages"]] == \
        ["admitted", "queued", "assigned", "prefill", "first_token",
         "streaming", "completed"]
    assert timeline["latency_s"] == pytest.approx(6.0)
    assert durations_partition_latency(timeline)
    assert validate_timeline(timeline) == []
    text = hub.render(prefix="tpu_router")
    assert ('tpu_router_request_stage_seconds_count'
            '{lane="interactive",stage="queued"} 1') in text
    assert "tpu_router_traces_closed 1" in text
    assert "tpu_router_traces_open 0" in text
    # no selfclock -> the overhead histogram is never observed
    assert "tpu_router_proxy_overhead_seconds" not in text


def test_recorder_stage_edges_are_noops_when_unknown_or_repeated():
    rec = RequestTraceRecorder(clock=FakeClock(0.0))
    rec.stage(99, "queued")        # never begun: no-op
    rec.token_appended(99)
    assert rec.open_count() == 0
    rec.begin(1)
    rec.stage(1, "queued")
    rec.stage(1, "queued")         # same-stage repeat: no transition
    assert [s for _, s, _ in rec.open_timelines()[0]["stages"]] == \
        ["admitted", "queued"]


def test_recorder_splice_resumes_streaming_on_token():
    clock = FakeClock(0.0)
    rec = RequestTraceRecorder(clock=clock)
    rec.begin(1)
    for s in ("queued", "assigned", "prefill"):
        rec.stage(1, s)
    rec.token_appended(1)
    for s in ("drain", "export", "transfer", "adopt", "splice"):
        clock.advance(0.5)
        rec.stage(1, s)
    clock.advance(0.5)
    rec.token_appended(1)          # splice -> streaming
    rec.stage(1, "completed")
    timeline = rec.timeline(1)
    names = [s for _, s, _ in timeline["stages"]]
    assert names[-3:] == ["splice", "streaming", "completed"]
    assert all(m in names for m in MIGRATION_STAGES)
    assert rec.spliced == 1 and rec.splices == 1
    assert validate_timeline(timeline) == []


def test_recorder_parent_context_joins_trace():
    rec = RequestTraceRecorder(clock=FakeClock(0.0))
    root = rec.begin(1)
    child = rec.begin(2, parent=root)
    assert child.trace_id == root.trace_id
    assert child.hop == root.hop + 1
    assert child.span_id != root.span_id
    # re-begin keeps the first timeline and returns its context
    again = rec.begin(1)
    assert again == root
    assert rec.open_count() == 2


def test_recorder_fixed_memory_bounds():
    clock = FakeClock(0.0)
    rec = RequestTraceRecorder(clock=clock, max_closed=2, max_open=3)
    for rid in range(5):
        rec.begin(rid)
    assert rec.open_count() == 3 and rec.dropped == 2
    for rid in (2, 3, 4):
        rec.stage(rid, "queued")
        rec.stage(rid, "shed")
    assert rec.open_count() == 0 and rec.closed == 3
    ring = rec.timelines()
    assert [t["rid"] for t in ring] == [3, 4]    # last-2 retained
    payload = rec.payload(last=1)
    assert payload["closed"] == 3 and payload["dropped"] == 2
    assert payload["ring_capacity"] == 2
    assert [t["rid"] for t in payload["last"]] == [4]
    assert payload["stage_totals"]["queued"]["count"] == 3


def test_recorder_selfclock_measures_overhead():
    clock = FakeClock(0.0)
    hub = MetricsHub()
    ticks = iter(x * 0.001 for x in range(100))
    rec = RequestTraceRecorder(clock=clock, metrics=hub,
                               selfclock=lambda: next(ticks))
    rec.begin(1)
    with rec.timer(1, "route"):
        pass                        # one selfclock tick = 1 ms
    rec.stage(1, "queued")
    rec.stage(1, "shed")
    timeline = rec.timeline(1)
    assert timeline["overhead_s"] == pytest.approx(0.001)
    assert timeline["self"]["route"] == pytest.approx(0.001)
    text = hub.render(prefix="tpu_router")
    assert ('tpu_router_proxy_overhead_seconds_count'
            '{lane="interactive"} 1') in text


def test_trace_payload_open_and_closed():
    clock = FakeClock(0.0)
    rec = RequestTraceRecorder(clock=clock)
    rec.begin(1)
    rec.stage(1, "queued")
    clock.advance(1.0)
    open_view = rec.trace_payload(1)
    assert open_view["open"] is True
    assert open_view["durations"] == {"admitted": 0.0}
    rec.stage(1, "shed")
    closed_view = rec.trace_payload(1)
    assert closed_view["open"] is False
    assert closed_view["terminal"] == "shed"
    assert rec.trace_payload(404) is None


# ------------------------------------- the integrity invariant bites


class _StubRouter:
    def __init__(self, successes=0, fallbacks=0):
        self.migration_successes = successes
        self.migration_fallbacks = fallbacks
        self.requests = {}


def _view(recorder, router):
    return CampaignView(tick=1, t=15.0, nodes={}, keys=None, budget=1,
                        fault_notready=set(), leaders=[],
                        recorder_events=[], alert_status={},
                        router=router, reqtrace=recorder)


def test_invariant_skips_without_recorder():
    inv = RequestTraceIntegrityInvariant()
    assert inv.check(_view(None, _StubRouter())) == []


def test_invariant_catches_illegal_transition_once():
    rec = RequestTraceRecorder(clock=FakeClock(0.0))
    rec.begin(1)
    rec.stage(1, "streaming")      # admitted -> streaming: illegal
    rec.stage(1, "completed")
    inv = RequestTraceIntegrityInvariant()
    out = inv.check(_view(rec, _StubRouter()))
    assert len(out) == 1 and "illegal stage transition" in out[0].detail
    # stateful: the same closed timeline is not re-reported
    assert inv.check(_view(rec, _StubRouter())) == []


def test_invariant_reconciles_migration_ledgers():
    rec = RequestTraceRecorder(clock=FakeClock(0.0))
    inv = RequestTraceIntegrityInvariant()
    # recorder saw no splice but the router counted a migration
    out = inv.check(_view(rec, _StubRouter(successes=1)))
    assert len(out) == 1 and "migration" in out[0].detail
    # reported once per distinct mismatch
    assert inv.check(_view(rec, _StubRouter(successes=1))) == []
    out = inv.check(_view(rec, _StubRouter(fallbacks=2)))
    assert len(out) == 1 and "fallback" in out[0].detail


# ------------------------------------------- campaign: provably free


REQTRACE_SCENARIO = {
    "name": "reqtrace-invariance",
    "max_ticks": 300,
    "fleet": {"slices": 2, "hosts_per_slice": 4, "solo_nodes": 0},
    "upgrade_at": 30.0,
    "faults": [
        {"type": "mid-stream-kill", "at": 60.0, "duration": 90.0,
         "slices": [0]},
        {"type": "kv-transfer-flake", "at": 150.0, "duration": 120.0,
         "rate": 0.6, "slices": [0, 1]},
    ],
}


def _token_capture(store):
    """Per-tick snapshot of every request's client-visible token stream
    — the 'sim tokens byte-identical' half of the transparency pin."""
    def hook(router=None, tick=None, **kw):
        store.append({rid: list(req.stream)
                      for rid, req in router.requests.items()})
    return hook


def test_campaign_identical_with_reqtrace_on_and_off(tmp_path):
    """ACCEPTANCE: tracing is free — the same seed converges identically
    (router stats, per-tick sim token streams, invariants, fault trace)
    with the request recorder wired in and without it."""
    sc = parse_scenario(REQTRACE_SCENARIO)
    tokens_off, tokens_on = [], []
    off = run_scenario(sc, seed=13, workdir=str(tmp_path / "off"),
                       hooks=[_token_capture(tokens_off)],
                       reqtrace=False)
    on = run_scenario(sc, seed=13, workdir=str(tmp_path / "on"),
                      hooks=[_token_capture(tokens_on)])
    assert off.violations == [] and on.violations == []
    assert off.converged and on.converged
    assert (off.ticks, off.failovers, off.modelled_s) == \
        (on.ticks, on.failovers, on.modelled_s)
    assert off.trace == on.trace
    assert off.router_stats == on.router_stats
    assert tokens_off == tokens_on
    assert off.reqtrace_payload is None
    assert on.reqtrace_payload is not None
    assert on.reqtrace_payload["closed"] > 0


def test_campaign_reqtrace_deterministic_per_seed(tmp_path):
    """Same seed → byte-identical timelines (ids, stages, FakeClock
    stamps, aggregates) across two runs."""
    sc = parse_scenario(REQTRACE_SCENARIO)
    r1 = run_scenario(sc, seed=9, workdir=str(tmp_path / "a"))
    r2 = run_scenario(sc, seed=9, workdir=str(tmp_path / "b"))
    assert r1.reqtrace_payload is not None
    assert json.dumps(r1.reqtrace_payload, sort_keys=True) == \
        json.dumps(r2.reqtrace_payload, sort_keys=True)


def test_campaign_timelines_survive_migration_faults(tmp_path):
    """Under mid-stream kills and KV-transfer flakes every closed
    timeline stays a legal walk, migration stages appear iff the router
    counted a migration, and the per-stage durations partition each
    request's latency (the invariant asserts all of this every tick —
    this test additionally checks the final ring directly)."""
    sc = parse_scenario(REQTRACE_SCENARIO)
    res = run_scenario(sc, seed=13, workdir=str(tmp_path))
    assert res.violations == [], "\n".join(map(str, res.violations))
    payload = res.reqtrace_payload
    assert payload["closed"] >= res.router_stats["completed"] > 0
    for timeline in payload["last"]:
        assert validate_timeline(timeline, closed=True) == []
    if res.router_stats["migrations"] > 0:
        assert payload["spliced"] > 0
