"""Fleet-health end-to-end on the fake cluster (the ISSUE-2 acceptance
scenarios): a crash-looping device-plugin on one host of a 4-host slice
quarantines and repairs the WHOLE slice atomically through the upgrade
state machine and uncordons; a flapping signal under the damping window
triggers no remediation; concurrent remediation + rolling upgrade respect
one shared maxUnavailable budget."""

from k8s_operator_libs_tpu.api.v1alpha1 import (DrainSpec,
                                                DriverUpgradePolicySpec)
from k8s_operator_libs_tpu.health import consts as hconsts
from k8s_operator_libs_tpu.health.classifier import ClassifierConfig
from k8s_operator_libs_tpu.health.monitor import HealthOptions
from k8s_operator_libs_tpu.health.remediation import RemediationPolicy
from k8s_operator_libs_tpu.tpu.operator import (ManagedComponent,
                                                TPUOperator)
from k8s_operator_libs_tpu.tpu.topology import (GKE_ACCELERATOR_LABEL,
                                                GKE_NODEPOOL_LABEL,
                                                GKE_TOPOLOGY_LABEL)
from k8s_operator_libs_tpu.upgrade.consts import UpgradeState
from k8s_operator_libs_tpu.upgrade.util import KeyFactory

NS = "kube-system"
TICK = 15.0

KEYS = KeyFactory("libtpu")


def slice_labels(pool):
    return {GKE_ACCELERATOR_LABEL: "tpu-v5-lite-podslice",
            GKE_TOPOLOGY_LABEL: "4x4", GKE_NODEPOOL_LABEL: pool}


def add_slice(cluster, ds, pool, revision_hash="v1"):
    hosts = [f"{pool}-h{i}" for i in range(4)]
    for h in hosts:
        cluster.add_node(h, labels=slice_labels(pool))
        cluster.add_pod(f"drv-{h}", h, namespace=NS, owner_ds=ds,
                        revision_hash=revision_hash)
    return hosts


def health_options(**overrides):
    opts = dict(
        classifier=ClassifierConfig(damping_seconds=30.0,
                                    persist_seconds=60.0),
        policy=RemediationPolicy(recovery_seconds=45.0,
                                 backoff_base_seconds=60.0))
    opts.update(overrides)
    return HealthOptions(**opts)


def make_operator(cluster, clock, health, max_unavailable="100%"):
    return TPUOperator(
        cluster.client,
        components=[ManagedComponent(
            name="libtpu", namespace=NS, driver_labels={"app": "libtpu"},
            policy=DriverUpgradePolicySpec(
                auto_upgrade=True, max_parallel_upgrades=0,
                max_unavailable=max_unavailable,
                drain=DrainSpec(enable=True, force=True,
                                timeout_second=60)))],
        recorder=cluster.recorder, clock=clock, synchronous=True,
        health=health)


def node_view(cluster, name):
    return cluster.client.direct().get_node(name)


def test_crashloop_quarantines_and_repairs_whole_slice(cluster, clock):
    """One sick host of a 4-host slice → the FULL slice quarantines,
    repairs slice-atomically through the upgrade pipeline (driver pod
    recreated), and uncordons as a unit."""
    ds = cluster.add_daemonset("libtpu", namespace=NS,
                               labels={"app": "libtpu"}, revision_hash="v1")
    hosts = add_slice(cluster, ds, "pool-a")
    old_uid = cluster.client.direct().get_pod(NS, "drv-pool-a-h0").metadata.uid
    op = make_operator(cluster, clock, health_options())

    cluster.set_pod_status(NS, "drv-pool-a-h0", ready=False, restart_count=12)

    quarantined_ticks = 0
    repairs_injected = []
    states_seen = {h: set() for h in hosts}
    converged = False
    for _ in range(120):
        op.reconcile()
        cluster.reconcile_daemonsets()
        nodes = {h: node_view(cluster, h) for h in hosts}
        q = {h for h, n in nodes.items()
             if hconsts.QUARANTINE_LABEL in n.metadata.labels}
        if q:
            # slice atomicity of quarantine: never a partial quarantine,
            # and every quarantined member is cordoned + tainted
            assert q == set(hosts), q
            assert all(n.spec.unschedulable for n in nodes.values())
            assert all(any(t.key == hconsts.QUARANTINE_TAINT_KEY
                           for t in n.spec.taints) for n in nodes.values())
            quarantined_ticks += 1
            # no member returns to service while the slice is quarantined
            assert not any(not n.spec.unschedulable for n in nodes.values())
        for h, n in nodes.items():
            states_seen[h].add(n.metadata.labels.get(KEYS.state_label, ""))
        if op.last_health is not None:
            repairs_injected.extend(
                op.last_health.actions.repairs_injected)
        if (quarantined_ticks
                and all(not n.spec.unschedulable for n in nodes.values())
                and not any(hconsts.QUARANTINE_LABEL in n.metadata.labels
                            for n in nodes.values())):
            converged = True
            break
        clock.advance(TICK)

    assert converged, "slice never quarantined+repaired+uncordoned"
    assert quarantined_ticks > 0
    assert repairs_injected == ["slice/pool-a"]
    # the repair rode the upgrade state machine: every host traversed the
    # pipeline (slice-atomic admission + barriers), not some ad-hoc path
    for h in hosts:
        assert UpgradeState.DRAIN_REQUIRED in states_seen[h] \
            or UpgradeState.WAIT_FOR_JOBS_REQUIRED in states_seen[h], \
            (h, states_seen[h])
        assert node_view(cluster, h).metadata.labels.get(KEYS.state_label) \
            == UpgradeState.DONE
    # the failing driver pod was recreated (the fake DS controller names
    # replacements <ds>-<node>), fresh and ready
    pods_h0 = cluster.client.direct().list_pods(
        namespace=NS, field_node_name="pool-a-h0")
    assert len(pods_h0) == 1
    assert pods_h0[0].metadata.uid != old_uid
    assert all(cs.ready for cs in pods_h0[0].status.container_statuses)
    # quarantine bookkeeping cleaned up, backoff history retained
    n0 = node_view(cluster, hosts[0])
    assert hconsts.REPAIR_ANNOTATION not in n0.metadata.annotations
    assert n0.metadata.annotations[hconsts.REPAIR_ATTEMPTS_ANNOTATION] == "1"
    assert n0.spec.taints == []
    # events tell the story
    reasons = [e.message for e in cluster.recorder.events
               if e.reason == "FleetHealth"]
    assert any("Quarantined slice/pool-a" in m for m in reasons)
    assert any("slice-atomic repair" in m for m in reasons)
    assert any("Quarantine lifted" in m for m in reasons)


def test_flapping_signal_triggers_no_remediation(cluster, clock):
    """A signal bouncing faster than the damping window holds the node at
    degraded forever: no cordon, no taint, no repair injection."""
    ds = cluster.add_daemonset("libtpu", namespace=NS,
                               labels={"app": "libtpu"}, revision_hash="v1")
    hosts = add_slice(cluster, ds, "pool-a")
    op = make_operator(cluster, clock, health_options(
        classifier=ClassifierConfig(damping_seconds=100.0,
                                    persist_seconds=200.0)))

    saw_degraded = False
    for tick in range(40):
        # bounce: crash-looping on even ticks, apparently fine on odd
        cluster.set_pod_status(NS, "drv-pool-a-h0",
                               ready=(tick % 2 == 1), restart_count=12)
        op.reconcile()
        cluster.reconcile_daemonsets()
        for h in hosts:
            n = node_view(cluster, h)
            assert not n.spec.unschedulable, (tick, h)
            assert n.spec.taints == []
            assert hconsts.QUARANTINE_LABEL not in n.metadata.labels
            assert hconsts.REPAIR_ANNOTATION not in n.metadata.annotations
            assert KEYS.upgrade_requested_annotation not in \
                n.metadata.annotations
            verdict = n.metadata.labels.get(hconsts.VERDICT_LABEL)
            assert verdict in (None, "degraded"), (tick, h, verdict)
            if verdict == "degraded":
                saw_degraded = True
        clock.advance(TICK)
    assert saw_degraded  # the flap was observed, just never acted on
    assert op.last_health.actions.repairs_injected == []


def test_remediation_and_rolling_upgrade_share_budget(cluster, clock):
    """Two 4-host slices, maxUnavailable=50% (4 nodes): pool-a needs a
    version upgrade, pool-b is sick. The rolling upgrade consumes the
    budget first, health DEFERS pool-b's quarantine until pool-a is back
    in service, then quarantines + injects the repair — and at no tick do
    the two mechanisms together take more than 4 nodes out of service."""
    ds = cluster.add_daemonset("libtpu", namespace=NS,
                               labels={"app": "libtpu"}, revision_hash="v1")
    hosts_a = add_slice(cluster, ds, "pool-a", revision_hash="v1")
    cluster.bump_daemonset_revision("libtpu", NS, "v2")
    # pool-b is already at v2 (no drift): only health can repair it
    hosts_b = add_slice(cluster, ds, "pool-b", revision_hash="v2")
    every = hosts_a + hosts_b

    op = make_operator(
        cluster, clock,
        health_options(
            classifier=ClassifierConfig(damping_seconds=15.0,
                                        persist_seconds=30.0),
            policy=RemediationPolicy(recovery_seconds=30.0,
                                     backoff_base_seconds=60.0,
                                     max_unavailable="50%")),
        max_unavailable="50%")

    cluster.set_pod_status(NS, "drv-pool-b-h0", ready=False,
                           restart_count=12)

    max_unavailable_seen = 0
    deferred = repaired = False
    converged = False
    for _ in range(200):
        op.reconcile()
        cluster.reconcile_daemonsets()
        nodes = {h: node_view(cluster, h) for h in every}
        unavailable = sum(1 for n in nodes.values()
                          if n.spec.unschedulable or not n.is_ready())
        max_unavailable_seen = max(max_unavailable_seen, unavailable)
        # THE shared-budget invariant
        assert unavailable <= 4, unavailable
        if op.last_health is not None:
            if op.last_health.actions.deferred_slices:
                deferred = True
                # deferral happened because the rolling upgrade held the
                # budget: pool-a is the occupant — cordoned, or admitted
                # and about to cordon (state cordon-required)
                assert any(
                    nodes[h].spec.unschedulable
                    or nodes[h].metadata.labels.get(KEYS.state_label)
                    == UpgradeState.CORDON_REQUIRED
                    for h in hosts_a)
            if op.last_health.actions.repairs_injected:
                repaired = True
        pods = cluster.client.direct().list_pods(
            namespace=NS, label_selector={"app": "libtpu"})
        all_v2 = len(pods) == 8 and all(
            p.metadata.labels["controller-revision-hash"] == "v2"
            and all(cs.ready for cs in p.status.container_statuses)
            for p in pods)
        if (all_v2
                and all(not n.spec.unschedulable for n in nodes.values())
                and not any(hconsts.QUARANTINE_LABEL in n.metadata.labels
                            for n in nodes.values())
                and all(n.metadata.labels.get(KEYS.state_label)
                        == UpgradeState.DONE for n in nodes.values())):
            converged = True
            break
        clock.advance(TICK)

    assert converged, "fleet never converged to upgraded + repaired"
    assert deferred, "quarantine was never budget-deferred"
    assert repaired, "health never injected the pool-b repair"
    assert max_unavailable_seen == 4  # the budget was actually used


def test_operator_without_health_is_unchanged(cluster, clock):
    """health=None keeps the legacy reconcile surface: no monitor, no
    health writes, reconcile() returns the same shape."""
    ds = cluster.add_daemonset("libtpu", namespace=NS,
                               labels={"app": "libtpu"}, revision_hash="v1")
    add_slice(cluster, ds, "pool-a")
    op = make_operator(cluster, clock, health=None)
    states = op.reconcile()
    assert set(states) == {"libtpu"}
    assert op.health_monitor is None and op.last_health is None
    for n in cluster.client.direct().list_nodes():
        assert hconsts.VERDICT_LABEL not in n.metadata.labels
        assert hconsts.QUARANTINE_LABEL not in n.metadata.labels


def test_operator_binary_health_config_quarantines_and_exports_metrics(
        tmp_path):
    """cmd/operator.py wiring: the YAML health: section turns the monitor
    on, a crash-looping driver pod gets its node quarantined, and the
    health gauges ride the shared /metrics endpoint in valid exposition
    format (satellite: wiring + metrics acceptance)."""
    import importlib.util
    import os
    import threading
    import time
    import urllib.request

    import yaml

    from k8s_operator_libs_tpu.core.fakecluster import FakeCluster
    from k8s_operator_libs_tpu.core.httpapi import FakeAPIServer

    spec = importlib.util.spec_from_file_location(
        "operator_cli_health", os.path.join(os.path.dirname(__file__), "..",
                                            "cmd", "operator.py"))
    op = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(op)

    cluster = FakeCluster()
    ds = cluster.add_daemonset("libtpu", namespace="tpu",
                               labels={"app": "d"}, revision_hash="v1")
    for i in range(2):
        cluster.add_node(f"n{i}")
        cluster.add_pod(f"d-{i}", f"n{i}", namespace="tpu", owner_ds=ds,
                        revision_hash="v1")
    cluster.set_pod_status("tpu", "d-0", ready=False, restart_count=12)

    srv = FakeAPIServer(cluster).start()
    kubeconfig = {
        "current-context": "fake",
        "contexts": [{"name": "fake",
                      "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": {"server": srv.base_url}}],
        "users": [{"name": "u", "user": {}}],
    }
    kc = tmp_path / "kubeconfig"
    kc.write_text(yaml.safe_dump(kubeconfig))
    cfg = tmp_path / "operator.yaml"
    cfg.write_text(yaml.safe_dump({
        "components": [{"name": "libtpu", "namespace": "tpu",
                        "driverLabels": {"app": "d"},
                        "policy": {"autoUpgrade": True}}],
        # dampingSeconds 0 = instant confirm; huge persistSeconds keeps the
        # verdict transient, so this test exercises quarantine + metrics
        # without waiting out a real-clock repair pipeline
        "health": {"repairComponent": "libtpu", "dampingSeconds": 0,
                   "persistSeconds": 100000},
    }))
    stop = threading.Event()
    captured = {}
    rcs = []
    t = threading.Thread(target=lambda: rcs.append(op.main(
        ["--config", str(cfg), "--kubeconfig", str(kc), "--uncached",
         "--interval", "0.1", "--metrics-port", "0"],
        stop=stop, on_ready=lambda s: captured.update(server=s))))
    t.start()
    try:
        deadline = time.time() + 20
        body = ""
        while time.time() < deadline:
            n0 = cluster.client.direct().get_node("n0")
            server = captured.get("server")
            if (server is not None
                    and hconsts.QUARANTINE_LABEL in n0.metadata.labels):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{server.port}/metrics") as r:
                    body = r.read().decode()
                if "tpu_operator_health_quarantined_nodes" in body:
                    break
            time.sleep(0.1)
        n0 = cluster.client.direct().get_node("n0")
        assert n0.spec.unschedulable
        assert n0.metadata.labels[hconsts.QUARANTINE_LABEL] == \
            "unhealthy-transient"
        # healthy sibling untouched (single-host groups: no TPU labels)
        assert not cluster.client.direct().get_node("n1").spec.unschedulable
        assert ('tpu_operator_health_quarantined_nodes{component="libtpu"}'
                ' 1' in body), body
        assert "# HELP tpu_operator_health_quarantined_nodes" in body
        assert 'tpu_operator_total_managed_nodes{component="libtpu"} 2' \
            in body
    finally:
        stop.set()
        t.join(timeout=15)
        srv.stop()
    assert rcs == [0]


def test_status_cli_shows_quarantine_column(cluster, clock, capsys):
    """cmd/status.py HEALTH column: '-' when the health subsystem never
    ran, '<verdict>/Q' for quarantined nodes (satellite #2)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "status_cli_health", os.path.join(os.path.dirname(__file__), "..",
                                          "cmd", "status.py"))
    status = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(status)

    ds = cluster.add_daemonset("libtpu", namespace=NS,
                               labels={"app": "libtpu"}, revision_hash="v1")
    hosts = add_slice(cluster, ds, "pool-a")
    argv = ["--component", "libtpu", "--namespace", NS,
            "--selector", "app=libtpu"]
    # health subsystem never ran -> every row degrades to "-"
    assert status.main(argv, client=cluster.client.direct()) == 0
    out = capsys.readouterr().out
    assert "HEALTH" in out and "0 quarantined" in out

    op = make_operator(cluster, clock, health_options())
    cluster.set_pod_status(NS, "drv-pool-a-h0", ready=False,
                           restart_count=12)
    for _ in range(10):
        op.reconcile()
        clock.advance(TICK)
        nodes = [node_view(cluster, h) for h in hosts]
        if all(hconsts.QUARANTINE_LABEL in n.metadata.labels
               for n in nodes):
            break
    rc = status.main(argv, client=cluster.client.direct())
    out = capsys.readouterr().out
    assert "/Q" in out and "4 quarantined" in out
    assert rc in (0, 3)  # quarantine alone must not read as failed
